"""Pallas TPU kernel: flash attention (fused online-softmax attention).

The pure-JAX attention family (tpunet/ops/attention.py) bounds MEMORY
via lax.scan online softmax, but XLA still materializes each [bq, Tk]
score block in HBM between the two einsums. This kernel fuses
scores -> online softmax -> weighted values into one VMEM-resident
program per (batch, head, q-block): scores never leave VMEM, the two
matmuls hit the MXU back-to-back, and the running (m, l, acc) state
lives in scratch that persists across the sequential k-block grid axis
(the standard TPU FlashAttention schedule).

Design notes:
- Grid (B, H, nq, nk); TPU iterates the LAST axis sequentially on one
  core, so VMEM scratch carries the online-softmax state across k
  blocks; @pl.when(k==0) initializes, @pl.when(k==nk-1) finalizes.
- m/l scratch is (bq, 128): Mosaic wants the lane dim, values are
  broadcast across it and read back as [:, :1].
- Causal masking uses the same "explicitly zero masked probabilities"
  convention as tpunet/ops/attention.py (fully-masked rows emit zeros,
  not uniform attention).
- float32 accumulation regardless of compute dtype (MXU-native bf16 in,
  f32 out of the dot).
- Backward: two more Pallas kernels (the standard flash backward) —
  probabilities are recomputed per block from the saved log-sum-exp, so
  nothing O(Tq x Tk) touches HBM in either direction. dQ accumulates
  over k blocks on grid (B,H,nq,nk); dK/dV accumulate over q blocks on
  the transposed grid (B,H,nk,nq); delta = rowsum(dO * O) is plain XLA.
- Off-TPU the public entry falls back to dense_attention (the Pallas
  interpreter is far too slow for a hot path); tests exercise the real
  kernel body on CPU with interpret=True, the same scheme as
  tpunet/ops/depthwise.py.

Measured on a real TPU v5e chip (B=4, T=4096, H=8, D=64, causal,
bfloat16; synchronized by fetching a data-dependent output element;
scripts/bench_flash.py):

  round 1 (rectangular causal grid + @pl.when skip):
    fwd: flash 10.7 ms vs dense 25.6 ms vs blockwise 17.1 ms
  round 2 (fused TRIANGULAR causal grids — fwd, dQ, AND dK/dV (upper
  triangle via point reflection of the same inversion) — dead copies
  elided on the remaining rectangular cross-length paths):
    fwd: flash 8.2-8.6 ms (-20% vs round 1; ~2.5x dense's 20.8 ms)
    fwd+bwd: flash 12.8 ms vs dense 39.8 ms (3.1x) vs blockwise 50.7 ms
    segments (4 packed docs): 8.0 ms fwd — masking costs ~nothing

End-to-end LM training (fwd + bwd + Adam, the numbers that matter):
357k tok/s at T=2048 vs 157k dense (2.3x; was 339k with the
rectangular grid), and 135k tok/s at T=8192+remat vs 28k blockwise —
the flash backward kernels remove the O(T²) HBM traffic that binds the
dense backward (scripts/bench_lm.py; full table in README.md).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpunet.ops.attention import (_NEG_INF, _divisor_block,
                                  dense_attention)


def _tri_qi_ki(t):
    """Invert the row-major lower-triangle linearization: step t ->
    (qi, ki) with ki <= qi; t = qi*(qi+1)/2 + ki. Float sqrt with an
    exact integer correction (sqrt rounding can be off by one at the
    triangular-number boundaries)."""
    qi = ((jnp.sqrt(8.0 * t.astype(jnp.float32) + 1.0) - 1.0) / 2.0
          ).astype(jnp.int32)
    qi = jnp.where(t < qi * (qi + 1) // 2, qi - 1, qi)
    qi = jnp.where(t >= (qi + 1) * (qi + 2) // 2, qi + 1, qi)
    return qi, t - qi * (qi + 1) // 2


def _tri_ki_qi_upper(t, nq: int):
    """Invert the row-major UPPER-triangle linearization used by the
    dK/dV grid: rows are k blocks, each accumulating q blocks
    qi = ki..nq-1. Reuses the tested lower-triangle inversion through a
    point reflection: enumerating the upper triangle forward equals
    enumerating the lower one backward with both coordinates flipped.
    """
    total = nq * (nq + 1) // 2
    lo_qi, lo_ki = _tri_qi_ki(total - 1 - t)
    return nq - 1 - lo_qi, nq - 1 - lo_ki      # (ki, qi)


def _use_tri(causal, tq, tk, bq, bk) -> bool:
    """Triangular-grid eligibility: causal SELF-attention with square
    blocks — every diagonal block is then partially valid and every
    sub-diagonal block fully valid, so the lower triangle enumerates
    exactly the needed (qi, ki) pairs. The sqrt inversion in
    _tri_qi_ki runs in float32: its ~2^-24 relative error keeps the
    qi estimate within reach of the ±1 integer correction only while
    the triangle size stays under 2**23 (verified exhaustively at
    nq=4095); beyond that (tiny blocks on a very long sequence) fall
    back to the rectangular grid rather than risk silently enumerating
    wrong pairs."""
    if not (causal and tq == tk and bq == bk):
        return False
    nq = -(-tq // bq)
    return nq * (nq + 1) // 2 < 2 ** 23


def _seg_mask(qseg_ref, kseg_ref):
    """[bq, bk] same-segment mask from the lane-broadcast q segment ids
    ([bq, 128], read [:, :1]) and sublane-broadcast kv segment ids
    ([8, bk], read [:1, :]) — the stock TPU flash kernel's layouts."""
    return qseg_ref[0, :, :1] == kseg_ref[0, :1, :]


def _kernel(q_ref, k_ref, v_ref, *refs,
            scale: float, causal: bool, bq: int, bk: int, nk: int,
            tq: int, tk: int, with_lse: bool, tri: bool,
            with_segments: bool):
    # Optional operands/outputs resolved by arity: segment-id inputs
    # come after v; the lse output exists only on the residual
    # (training-forward) variant — the forward-only path skips its HBM
    # writes entirely.
    if with_segments:
        qseg_ref, kseg_ref, *refs = refs
    o_ref, *refs = refs
    if with_lse:
        lse_ref, m_ref, l_ref, acc_ref = refs
    else:
        m_ref, l_ref, acc_ref = refs
    if tri:
        # Fused lower-triangular grid: only needed (qi, ki) pairs exist,
        # no dead steps at all (VERDICT r1 item 5).
        qi, ki = _tri_qi_ki(pl.program_id(2))
        last, needed = ki == qi, True
    else:
        qi = pl.program_id(2)  # program ids are hoisted out of the
        ki = pl.program_id(3)  # pl.when bodies (cond sub-traces cannot
                               # bind pallas primitives in interpret mode)
        last = ki == nk - 1
        # Causal (cross-length rectangular grid): skip BOTH MXU dots for
        # k blocks entirely in this q block's future; their k/v copies
        # are also elided via the clamped index maps in _forward_impl.
        needed = ((qi + 1) * bq - 1 + (tk - tq) >= ki * bk) if causal \
            else True

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(needed)
    def _compute():
        # Dots run in the INPUT dtype with f32 accumulation (bf16 MXU
        # throughput; attention.py's einsums use the same convention).
        q = q_ref[0, 0]                            # [bq, D]
        k = k_ref[0, 0]                            # [bk, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = None
        if causal:
            # Global positions; the tk - tq offset matches
            # dense_attention's convention for decode windows.
            qpos = (qi * bq
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            kpos = (ki * bk
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
            mask = qpos + (tk - tq) >= kpos
        if with_segments:
            seg = _seg_mask(qseg_ref, kseg_ref)
            mask = seg if mask is None else mask & seg
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                     # [bq, bk]
        if mask is not None:
            # Fully-masked ROWS keep m at the init floor; exp(s - m)
            # there is 1, so zero the masked probabilities explicitly
            # (same convention as attention.py's _block_update).
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)             # [bq, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(last)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        if with_lse:
            # Log-sum-exp residual for the backward kernels: p can then
            # be recomputed per block as exp(s - lse) without the
            # running (m, l) state. Fully-masked rows keep the _NEG_INF
            # floor. Broadcast across the 128-lane dim (Mosaic block
            # constraint — the scheme of jax's stock TPU flash kernel).
            lse = jnp.where(l == 0.0, _NEG_INF,
                            m_ref[:, :1] + jnp.log(l_safe))
            lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _grid_and_maps(causal, bq, bk, nq, nk, tq, tk, b, h,
                   transposed: bool = False):
    """(grid, qmap, kvmap, qsegmap, ksegmap) for the flash pallas_calls.

    Default: the forward/dQ iteration order (q rows, k accumulated) —
    triangular when eligible (no dead steps at all), else rectangular
    with the k/v index maps CLAMPED for causal so dead blocks
    re-reference the previous block and Mosaic elides their copies
    (same-index revisiting).

    ``transposed``: the dK/dV order (k rows, q accumulated) — the upper
    triangle when eligible, else rectangular with the q-side maps
    clamped to the first needed q block of each k row (dead LEADING
    steps elided the same way).
    """
    if _use_tri(causal, tq, tk, bq, bk):
        if transposed:
            qb = lambda t: _tri_ki_qi_upper(t, nq)[1]
            kb = lambda t: _tri_ki_qi_upper(t, nq)[0]
        else:
            qb = lambda t: _tri_qi_ki(t)[0]
            kb = lambda t: _tri_qi_ki(t)[1]
        return ((b, h, nq * (nq + 1) // 2),
                lambda b, h, t: (b, h, qb(t), 0),
                lambda b, h, t: (b, h, kb(t), 0),
                lambda b, h, t: (b, qb(t), 0),
                lambda b, h, t: (b, 0, kb(t)))
    if transposed:
        if causal:
            qmin = lambda j: jnp.clip((j * bk - (tk - tq)) // bq,
                                      0, nq - 1)
            i_eff = lambda j, i: jnp.maximum(i, qmin(j))
        else:
            i_eff = lambda j, i: i
        return ((b, h, nk, nq),
                lambda b, h, j, i: (b, h, i_eff(j, i), 0),
                lambda b, h, j, i: (b, h, j, 0),
                lambda b, h, j, i: (b, i_eff(j, i), 0),
                lambda b, h, j, i: (b, 0, j))
    if causal:
        kmax = lambda i: jnp.clip(((i + 1) * bq - 1 + (tk - tq)) // bk,
                                  0, nk - 1)
        j_eff = lambda i, j: jnp.minimum(j, kmax(i))
    else:
        j_eff = lambda i, j: j
    return ((b, h, nq, nk),
            lambda b, h, i, j: (b, h, i, 0),
            lambda b, h, i, j: (b, h, j_eff(i, j), 0),
            lambda b, h, i, j: (b, i, 0),
            lambda b, h, i, j: (b, 0, j_eff(i, j)))


def _seg_operands(segment_ids, b, tq, tk):
    """(q_seg [B,Tq,128] lane-broadcast, kv_seg [B,8,Tk] sublane-
    broadcast) int32 — Mosaic-friendly layouts for 1-D per-token ids."""
    q_seg, kv_seg = segment_ids
    q_seg = jnp.asarray(q_seg, jnp.int32)
    kv_seg = jnp.asarray(kv_seg, jnp.int32)
    if q_seg.shape != (b, tq) or kv_seg.shape != (b, tk):
        raise ValueError(
            f"segment_ids shapes {q_seg.shape}/{kv_seg.shape} != "
            f"({(b, tq)}/{(b, tk)})")
    return (jnp.broadcast_to(q_seg[:, :, None], (b, tq, 128)),
            jnp.broadcast_to(kv_seg[:, None, :], (b, 8, tk)))


def _forward_impl(q, k, v, causal, scale, block_q, block_k, interpret,
                  with_lse: bool, segment_ids=None):
    from jax.experimental.pallas import tpu as pltpu

    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _divisor_block(tq, block_q)
    bk = _divisor_block(tk, block_k)
    nq, nk = tq // bq, tk // bk
    tri = _use_tri(causal, tq, tk, bq, bk)
    with_seg = segment_ids is not None

    qt = q.swapaxes(1, 2)                          # [B, H, Tq, D]
    kt = k.swapaxes(1, 2)
    vt = v.swapaxes(1, 2)
    kern = functools.partial(_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk, nk=nk, tq=tq, tk=tk,
                             with_lse=with_lse, tri=tri,
                             with_segments=with_seg)
    grid, qmap, kvmap, qsegmap, ksegmap = _grid_and_maps(
        causal, bq, bk, nq, nk, tq, tk, b, h)

    in_specs = [
        pl.BlockSpec((1, 1, bq, d), qmap),
        pl.BlockSpec((1, 1, bk, d), kvmap),
        pl.BlockSpec((1, 1, bk, d), kvmap),
    ]
    args = [qt, kt, vt]
    if with_seg:
        qs, ks = _seg_operands(segment_ids, b, tq, tk)
        in_specs += [pl.BlockSpec((1, bq, 128), qsegmap),
                     pl.BlockSpec((1, 8, bk), ksegmap)]
        args += [qs, ks]

    o_spec = pl.BlockSpec((1, 1, bq, d), qmap)
    o_shape = jax.ShapeDtypeStruct((b, h, tq, d), q.dtype)
    lse_spec = pl.BlockSpec((1, 1, bq, 128), qmap)
    lse_shape = jax.ShapeDtypeStruct((b, h, tq, 128), jnp.float32)
    # Named for byte/phase attribution (tpunet/obs/hlo_bytes.py
    # KERNEL_SCOPES): the kernel lowers to a custom call, not a dot
    # opcode, so the scope is what keeps it in the matmul bucket.
    with jax.named_scope("tpunet_flash_fwd"):
        res = pl.pallas_call(
            kern,
            grid=grid,
            in_specs=in_specs,
            out_specs=[o_spec, lse_spec] if with_lse else o_spec,
            out_shape=[o_shape, lse_shape] if with_lse else o_shape,
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),    # running max m
                pltpu.VMEM((bq, 128), jnp.float32),    # running normalizer l
                pltpu.VMEM((bq, d), jnp.float32),      # un-normalized acc
            ],
            interpret=interpret,
        )(*args)
    if with_lse:
        out, lse = res
        # out back to BTHD; lse squeezed to [B, H, Tq] (the kernel
        # wrote identical values across the 128-lane dim).
        return out.swapaxes(1, 2), lse[..., 0]
    return res.swapaxes(1, 2)


def _pallas_forward_res(q, k, v, causal, scale, block_q, block_k,
                        interpret):
    """-> (out [B,Tq,H,D], lse [B,H,Tq]) — the training forward.

    FIXED ARITY: registered with custom_partitioning, where a trailing
    default parameter would count as an operand slot — the segmented
    variants below are separate functions for exactly that reason.
    """
    return _forward_impl(q, k, v, causal, scale, block_q, block_k,
                         interpret, with_lse=True)


def _pallas_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    """-> out only; no lse HBM writes (the inference/eval forward)."""
    return _forward_impl(q, k, v, causal, scale, block_q, block_k,
                         interpret, with_lse=False)


# ---------------------------------------------------------------------------
# Backward kernels (the standard two-pass flash backward): probabilities
# are recomputed per block from the saved log-sum-exp, so nothing
# O(Tq x Tk) ever touches HBM. delta = rowsum(dO * O) is plain XLA.
#   dQ:    grid (B, H, nq, nk), accumulate over k blocks
#   dK/dV: grid (B, H, nk, nq), accumulate over q blocks
# ---------------------------------------------------------------------------


def _recompute_p_ds(q, k, v, do, lse, delta, glse, scale, causal,
                    qi, ki, bq, bk, tq, tk, seg=None):
    """Shared block math: p = exp(s - lse) (masked), dp = dO Vᵀ,
    ds = p * (dp - delta + glse) * scale. All f32; lse/delta/glse are
    [bq, 1]. ``glse`` is the cotangent of the lse OUTPUT (d lse/d s is
    exactly p, so it adds inside the parenthesis); zero for plain
    attention, nonzero when attention-state merging consumed the lse
    (the ring). ``seg`` is the optional [bq, bk] same-segment mask."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = qpos + (tk - tq) >= kpos
    if seg is not None:
        mask = seg if mask is None else mask & seg
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta + glse) * scale
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
               scale, causal, bq, bk, nk, tq, tk, with_glse, tri,
               with_segments):
    # glse is an input only when the lse output's cotangent is nonzero
    # (the ring's state merging); plain attention skips its HBM reads.
    if with_glse:
        glse_ref, *refs = refs
        glse = glse_ref[0, 0, :, :1]
    else:
        glse = 0.0
    if with_segments:
        qseg_ref, kseg_ref, *refs = refs
    dq_ref, dq_scr = refs
    if tri:
        qi, ki = _tri_qi_ki(pl.program_id(2))
        last, needed = ki == qi, True
    else:
        qi, ki = pl.program_id(2), pl.program_id(3)
        last = ki == nk - 1
        needed = ((qi + 1) * bq - 1 + (tk - tq) >= ki * bk) if causal \
            else True

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(needed)
    def _compute():
        k = k_ref[0, 0]
        seg = _seg_mask(qseg_ref, kseg_ref) if with_segments else None
        _, ds = _recompute_p_ds(q_ref[0, 0], k, v_ref[0, 0], do_ref[0, 0],
                                lse_ref[0, 0, :, :1], delta_ref[0, 0, :, :1],
                                glse,
                                scale, causal, qi, ki, bq, bk, tq, tk,
                                seg=seg)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(last)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                scale, causal, bq, bk, nq, tq, tk, with_glse,
                with_segments, tri):
    if with_glse:
        glse_ref, *refs = refs
        glse = glse_ref[0, 0, :, :1]
    else:
        glse = 0.0
    if with_segments:
        qseg_ref, kseg_ref, *refs = refs
    dk_ref, dv_ref, dk_scr, dv_scr = refs
    if tri:
        # Fused upper-triangular grid: row ki accumulates qi = ki..nq-1,
        # exactly the blocks a causal self-attention needs.
        ki, qi = _tri_ki_qi_upper(pl.program_id(2), nq)
        first, needed = qi == ki, True
    else:
        ki, qi = pl.program_id(2), pl.program_id(3)  # k outer, q inner
        first = qi == 0
        needed = ((qi + 1) * bq - 1 + (tk - tq) >= ki * bk) if causal \
            else True

    @pl.when(first)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        seg = _seg_mask(qseg_ref, kseg_ref) if with_segments else None
        p, ds = _recompute_p_ds(q, k_ref[0, 0], v_ref[0, 0], do,
                                lse_ref[0, 0, :, :1], delta_ref[0, 0, :, :1],
                                glse,
                                scale, causal, qi, ki, bq, bk, tq, tk,
                                seg=seg)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _pallas_backward(q, k, v, out, lse, do,
                     causal: bool, scale: float,
                     block_q: int, block_k: int, interpret: bool,
                     glse=None, segment_ids=None):
    """-> (dq, dk, dv), all in their input layouts/dtypes. ``glse``
    [B,H,Tq] is the lse output's cotangent — None (plain attention)
    compiles kernels without the extra input. ``segment_ids``:
    (q_seg [B,Tq], kv_seg [B,Tk]) for packed-sequence masking."""
    from jax.experimental.pallas import tpu as pltpu

    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _divisor_block(tq, block_q)
    bk = _divisor_block(tk, block_k)
    nq, nk = tq // bq, tk // bk
    with_glse = glse is not None
    with_seg = segment_ids is not None
    tri = _use_tri(causal, tq, tk, bq, bk)

    qt, kt, vt = (x.swapaxes(1, 2) for x in (q, k, v))
    dot_ = do.swapaxes(1, 2)
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1).swapaxes(1, 2)        # [B, H, Tq]
    # Row vectors carry a 128-lane dim for Mosaic's block constraint
    # (values identical across lanes; kernels read [:, :1]).
    lane = lambda x: jnp.broadcast_to(x.astype(jnp.float32)[..., None],
                                      x.shape + (128,))
    rows = [lane(lse), lane(delta)] + ([lane(glse)] if with_glse else [])
    segs = list(_seg_operands(segment_ids, b, tq, tk)) if with_seg else []

    # dQ: same grid/order as the forward — triangular when eligible,
    # else rectangular with clamped k/v maps (dead copies elided).
    grid_dq, qmap, kvmap, qsegmap, ksegmap = _grid_and_maps(
        causal, bq, bk, nq, nk, tq, tk, b, h)
    q_spec = pl.BlockSpec((1, 1, bq, d), qmap)
    row_spec = pl.BlockSpec((1, 1, bq, 128), qmap)
    kv_spec = pl.BlockSpec((1, 1, bk, d), kvmap)
    seg_specs = [pl.BlockSpec((1, bq, 128), qsegmap),
                 pl.BlockSpec((1, 8, bk), ksegmap)] if with_seg else []
    # Scoped like the fused-IR/depthwise backwards: a custom_vjp
    # backward carries no transpose( marker, so the tpunet_flash_bwd
    # scope is what keeps these kernels in the bwd phase.
    with jax.named_scope("tpunet_flash_bwd"):
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, causal=causal,
                              bq=bq, bk=bk, nk=nk, tq=tq, tk=tk,
                              with_glse=with_glse, tri=tri,
                              with_segments=with_seg),
            grid=grid_dq,
            in_specs=[q_spec, kv_spec, kv_spec, q_spec]
            + [row_spec] * len(rows) + seg_specs,
            out_specs=q_spec,
            out_shape=jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
            interpret=interpret,
        )(qt, kt, vt, dot_, *rows, *segs)

    # dK/dV: same block roles, transposed order — k block index is the
    # grid row, q block the accumulated axis (the upper triangle when
    # eligible).
    grid_dkv, qmap_t, kvmap_t, qsegmap_t, ksegmap_t = _grid_and_maps(
        causal, bq, bk, nq, nk, tq, tk, b, h, transposed=True)
    qi_spec = pl.BlockSpec((1, 1, bq, d), qmap_t)
    rowi_spec = pl.BlockSpec((1, 1, bq, 128), qmap_t)
    kvj_spec = pl.BlockSpec((1, 1, bk, d), kvmap_t)
    segi_specs = [pl.BlockSpec((1, bq, 128), qsegmap_t),
                  pl.BlockSpec((1, 8, bk), ksegmap_t)] if with_seg else []
    with jax.named_scope("tpunet_flash_bwd"):
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, causal=causal,
                              bq=bq, bk=bk, nq=nq, tq=tq, tk=tk,
                              with_glse=with_glse, with_segments=with_seg,
                              tri=tri),
            grid=grid_dkv,
            in_specs=[qi_spec, kvj_spec, kvj_spec, qi_spec]
            + [rowi_spec] * len(rows) + segi_specs,
            out_specs=[kvj_spec, kvj_spec],
            out_shape=[jax.ShapeDtypeStruct((b, h, tk, d), k.dtype),
                       jax.ShapeDtypeStruct((b, h, tk, d), v.dtype)],
            scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                            pltpu.VMEM((bk, d), jnp.float32)],
            interpret=interpret,
        )(qt, kt, vt, dot_, *rows, *segs)
    return (dq.swapaxes(1, 2), dk.swapaxes(1, 2), dv.swapaxes(1, 2))


# ---------------------------------------------------------------------------
# SPMD partitioning: a pallas_call is opaque to GSPMD, so without a rule
# the partitioner would all-gather the sharded batch onto every device
# (the same issue tpunet/ops/depthwise.py solves). Flash attention is
# trivially parallel over batch and heads (the grid's first two axes);
# seq and head_dim must stay replicated per shard.
# ---------------------------------------------------------------------------

from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

from tpunet.compat import def_partition_compat


def _q_spec_of(arg_shapes) -> P:
    sh = arg_shapes[0].sharding
    qs = list(sh.spec) if isinstance(sh, NamedSharding) else []
    qs += [None] * (4 - len(qs))
    return P(qs[0], None, qs[2], None)   # batch/head shardable


def _shardings(mesh, spec):
    """(4-D q/k/v/out sharding, 3-D lse/delta sharding) from the spec."""
    return (NamedSharding(mesh, spec),
            NamedSharding(mesh, P(spec[0], spec[2], None)))


def _infer_fwd(causal, scale, block_q, block_k, interpret, mesh,
               arg_shapes, result_shape):
    return _shardings(mesh, _q_spec_of(arg_shapes))[0]


def _partition_fwd(causal, scale, block_q, block_k, interpret, mesh,
                   arg_shapes, result_shape):
    s4, _ = _shardings(mesh, _q_spec_of(arg_shapes))

    def lower_fn(q, k, v):
        return _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret)

    return mesh, lower_fn, s4, (s4,) * 3


def _infer_res(causal, scale, block_q, block_k, interpret, mesh,
               arg_shapes, result_shape):
    s4, s3 = _shardings(mesh, _q_spec_of(arg_shapes))
    return (s4, s3)


def _partition_res(causal, scale, block_q, block_k, interpret, mesh,
                   arg_shapes, result_shape):
    s4, s3 = _shardings(mesh, _q_spec_of(arg_shapes))

    def lower_fn(q, k, v):
        return _pallas_forward_res(q, k, v, causal, scale, block_q,
                                   block_k, interpret)

    return mesh, lower_fn, (s4, s3), (s4,) * 3


def _infer_bwd(causal, scale, block_q, block_k, interpret, mesh,
               arg_shapes, result_shape):
    s4, _ = _shardings(mesh, _q_spec_of(arg_shapes))
    return (s4, s4, s4)


def _partition_bwd(causal, scale, block_q, block_k, interpret, mesh,
                   arg_shapes, result_shape):
    s4, s3 = _shardings(mesh, _q_spec_of(arg_shapes))

    def lower_fn(q, k, v, out, lse, do):
        return _pallas_backward(q, k, v, out, lse, do, causal, scale,
                                block_q, block_k, interpret)

    return mesh, lower_fn, (s4, s4, s4), (s4, s4, s4, s4, s3, s4)


_STATIC = dict(static_argnums=(3, 4, 5, 6, 7))
# Shardy wants need_replication factors sorted by introduction order
# (b, tq, h, d from q, then tk from k).
_REPL = ("tq", "d", "tk")

_partitioned = custom_partitioning(_pallas_forward, **_STATIC)
def_partition_compat(
    _partitioned,
    partition=_partition_fwd,
    infer_sharding_from_operands=_infer_fwd,
    sharding_rule="b tq h d, b tk h d, b tk h d -> b tq h d",
    need_replication_factors=_REPL,
)

_partitioned_res = custom_partitioning(_pallas_forward_res, **_STATIC)
def_partition_compat(
    _partitioned_res,
    partition=_partition_res,
    infer_sharding_from_operands=_infer_res,
    sharding_rule="b tq h d, b tk h d, b tk h d -> b tq h d, b h tq",
    need_replication_factors=_REPL,
)

def _pallas_backward_nog(q, k, v, out, lse, do, causal, scale, block_q,
                         block_k, interpret):
    """Fixed-arity wrapper for custom_partitioning (the glse=None
    default of _pallas_backward would otherwise count as an operand)."""
    return _pallas_backward(q, k, v, out, lse, do, causal, scale,
                            block_q, block_k, interpret)


_partitioned_bwd = custom_partitioning(
    _pallas_backward_nog, static_argnums=(6, 7, 8, 9, 10))
def_partition_compat(
    _partitioned_bwd,
    partition=_partition_bwd,
    infer_sharding_from_operands=_infer_bwd,
    sharding_rule=("b tq h d, b tk h d, b tk h d, b tq h d, b h tq, "
                   "b tq h d -> b tq h d, b tk h d, b tk h d"),
    need_replication_factors=_REPL,
)


def _make_flash(fwd_prim, res_prim, bwd_prim):
    """custom_vjp wiring shared by the partitioned (top-level jit) and
    shard-local (inside shard_map, where GSPMD has nothing left to
    partition — the Ulysses core) variants: the flash forward saves
    (q, k, v, out, lse) and the backward runs the two flash backward
    kernels (dQ; dK/dV) — nothing O(Tq x Tk) in HBM either direction."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
    def f(q, k, v, causal, scale, block_q, block_k, interpret):
        return fwd_prim(q, k, v, causal, scale, block_q, block_k,
                        interpret)

    def fwd(q, k, v, causal, scale, block_q, block_k, interpret):
        # Scopes on the custom_vjp bodies keep EVERYTHING they emit
        # (lane broadcasts, delta reductions, swapaxes copies — not
        # just the kernels) attributed to the right phase/bucket
        # (tpunet/obs/hlo_bytes.py KERNEL_SCOPES).
        with jax.named_scope("tpunet_flash_fwd"):
            out, lse = res_prim(q, k, v, causal, scale, block_q,
                                block_k, interpret)
        return out, (q, k, v, out, lse)

    def bwd(causal, scale, block_q, block_k, interpret, res, g):
        q, k, v, out, lse = res
        # Plain attention exposes no lse downstream: no glse operand.
        with jax.named_scope("tpunet_flash_bwd"):
            return bwd_prim(q, k, v, out, lse, g, causal, scale,
                            block_q, block_k, interpret)

    f.defvjp(fwd, bwd)
    return f


_flash = _make_flash(_partitioned, _partitioned_res, _partitioned_bwd)
_flash_local = _make_flash(_pallas_forward, _pallas_forward_res,
                           _pallas_backward)


# ---------------------------------------------------------------------------
# Segmented (packed-sequence) variants: separate FIXED-ARITY primitives
# — segment ids are real operands, and both custom_partitioning and
# custom_vjp count every non-static parameter as an operand slot, so
# the plain primitives cannot grow an optional argument.
# ---------------------------------------------------------------------------


def _pallas_forward_seg(q, k, v, qseg, kseg, causal, scale, block_q,
                        block_k, interpret):
    return _forward_impl(q, k, v, causal, scale, block_q, block_k,
                         interpret, with_lse=False,
                         segment_ids=(qseg, kseg))


def _pallas_forward_res_seg(q, k, v, qseg, kseg, causal, scale, block_q,
                            block_k, interpret):
    return _forward_impl(q, k, v, causal, scale, block_q, block_k,
                         interpret, with_lse=True,
                         segment_ids=(qseg, kseg))


def _pallas_backward_seg(q, k, v, qseg, kseg, out, lse, do, causal,
                         scale, block_q, block_k, interpret):
    return _pallas_backward(q, k, v, out, lse, do, causal, scale,
                            block_q, block_k, interpret,
                            segment_ids=(qseg, kseg))


def _seg_sharding(mesh, spec):
    """1-D-per-token operands shard over batch only."""
    return NamedSharding(mesh, P(spec[0], None))


def _partition_fwd_seg(causal, scale, block_q, block_k, interpret, mesh,
                       arg_shapes, result_shape):
    s4, _ = _shardings(mesh, _q_spec_of(arg_shapes))
    sseg = _seg_sharding(mesh, _q_spec_of(arg_shapes))

    def lower_fn(q, k, v, qseg, kseg):
        return _pallas_forward_seg(q, k, v, qseg, kseg, causal, scale,
                                   block_q, block_k, interpret)

    return mesh, lower_fn, s4, (s4, s4, s4, sseg, sseg)


def _partition_res_seg(causal, scale, block_q, block_k, interpret, mesh,
                       arg_shapes, result_shape):
    s4, s3 = _shardings(mesh, _q_spec_of(arg_shapes))
    sseg = _seg_sharding(mesh, _q_spec_of(arg_shapes))

    def lower_fn(q, k, v, qseg, kseg):
        return _pallas_forward_res_seg(q, k, v, qseg, kseg, causal,
                                       scale, block_q, block_k, interpret)

    return mesh, lower_fn, (s4, s3), (s4, s4, s4, sseg, sseg)


def _partition_bwd_seg(causal, scale, block_q, block_k, interpret, mesh,
                       arg_shapes, result_shape):
    s4, s3 = _shardings(mesh, _q_spec_of(arg_shapes))
    sseg = _seg_sharding(mesh, _q_spec_of(arg_shapes))

    def lower_fn(q, k, v, qseg, kseg, out, lse, do):
        return _pallas_backward_seg(q, k, v, qseg, kseg, out, lse, do,
                                    causal, scale, block_q, block_k,
                                    interpret)

    return (mesh, lower_fn, (s4, s4, s4),
            (s4, s4, s4, sseg, sseg, s4, s3, s4))


_SEG_STATIC = dict(static_argnums=(5, 6, 7, 8, 9))

_partitioned_seg = custom_partitioning(_pallas_forward_seg, **_SEG_STATIC)
def_partition_compat(
    _partitioned_seg,
    partition=_partition_fwd_seg,
    infer_sharding_from_operands=_infer_fwd,
    sharding_rule=("b tq h d, b tk h d, b tk h d, b tq, b tk "
                   "-> b tq h d"),
    need_replication_factors=_REPL,
)

_partitioned_res_seg = custom_partitioning(_pallas_forward_res_seg,
                                           **_SEG_STATIC)
def_partition_compat(
    _partitioned_res_seg,
    partition=_partition_res_seg,
    infer_sharding_from_operands=_infer_res,
    sharding_rule=("b tq h d, b tk h d, b tk h d, b tq, b tk "
                   "-> b tq h d, b h tq"),
    need_replication_factors=_REPL,
)

_partitioned_bwd_seg = custom_partitioning(
    _pallas_backward_seg, static_argnums=(8, 9, 10, 11, 12))
def_partition_compat(
    _partitioned_bwd_seg,
    partition=_partition_bwd_seg,
    infer_sharding_from_operands=_infer_bwd,
    sharding_rule=("b tq h d, b tk h d, b tk h d, b tq, b tk, "
                   "b tq h d, b h tq, b tq h d "
                   "-> b tq h d, b tk h d, b tk h d"),
    need_replication_factors=_REPL,
)


def _make_flash_seg(fwd_prim, res_prim, bwd_prim):
    """custom_vjp wiring for the segmented variants; segment ids are
    integer operands whose cotangents are symbolic-zero float0."""
    import numpy as np

    @functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
    def f(q, k, v, qseg, kseg, causal, scale, block_q, block_k,
          interpret):
        return fwd_prim(q, k, v, qseg, kseg, causal, scale, block_q,
                        block_k, interpret)

    def fwd(q, k, v, qseg, kseg, causal, scale, block_q, block_k,
            interpret):
        with jax.named_scope("tpunet_flash_fwd"):
            out, lse = res_prim(q, k, v, qseg, kseg, causal, scale,
                                block_q, block_k, interpret)
        return out, (q, k, v, qseg, kseg, out, lse)

    def bwd(causal, scale, block_q, block_k, interpret, res, g):
        q, k, v, qseg, kseg, out, lse = res
        with jax.named_scope("tpunet_flash_bwd"):
            dq, dk, dv = bwd_prim(q, k, v, qseg, kseg, out, lse, g,
                                  causal, scale, block_q, block_k,
                                  interpret)
        z = lambda a: np.zeros(a.shape, jax.dtypes.float0)
        return dq, dk, dv, z(qseg), z(kseg)

    f.defvjp(fwd, bwd)
    return f


_flash_seg = _make_flash_seg(_partitioned_seg, _partitioned_res_seg,
                             _partitioned_bwd_seg)
_flash_seg_local = _make_flash_seg(_pallas_forward_seg,
                                   _pallas_forward_res_seg,
                                   _pallas_backward_seg)


def local_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          causal: bool = False,
                          scale: Optional[float] = None,
                          block_q: int = 512,
                          block_k: int = 512,
                          interpret: Optional[bool] = None,
                          segment_ids=None) -> jax.Array:
    """flash_attention for use INSIDE shard_map bodies: per-shard
    arrays, no custom_partitioning wrapper. Same fallbacks (dense for
    degenerate lengths; dense off-TPU unless interpret=True) and the
    same optional packed-sequence ``segment_ids``."""
    return _entry(_flash_local, _flash_seg_local, q, k, v, causal, scale,
                  block_q, block_k, interpret, segment_ids=segment_ids)


# Attention-STATE variant for the ring: returns (out, lse) so partial
# results over different K/V blocks can be merged exactly
# (merge_attention_states). Differentiable: built from the same
# primitives, so the flash backward kernels serve it too.
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_local_state(q, k, v, causal, scale, block_q, block_k,
                       interpret):
    return _pallas_forward_res(q, k, v, causal, scale, block_q, block_k,
                               interpret)


def _fwd_local_state(q, k, v, causal, scale, block_q, block_k, interpret):
    with jax.named_scope("tpunet_flash_fwd"):
        out, lse = _pallas_forward_res(q, k, v, causal, scale, block_q,
                                       block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _bwd_local_state(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    go, glse = g
    # The lse output IS consumed downstream (the ring's state-merge
    # weights depend on it), so its cotangent carries real gradient:
    # d lse / d s = p, folded into ds inside the kernels.
    with jax.named_scope("tpunet_flash_bwd"):
        return _pallas_backward(q, k, v, out, lse, go, causal, scale,
                                block_q, block_k, interpret, glse=glse)


_flash_local_state.defvjp(_fwd_local_state, _bwd_local_state)


def local_flash_attention_state(q, k, v, *, causal=False, scale=None,
                                block_q: int = 512, block_k: int = 512,
                                interpret: Optional[bool] = None):
    """(out [B,Tq,H,D], lse [B,H,Tq]) over ONE K/V block — the ring
    core. No dense fallback here: the ring needs the lse state, and a
    shard's K/V block length is mesh-controlled (divisible), not
    user-degenerate. Off-TPU runs in interpret mode."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_local_state(q, k, v, causal, scale, block_q, block_k,
                              interpret)


def merge_attention_states(state_a, state_b):
    """Exactly combine two partial attention results computed over
    disjoint K/V blocks: each state is (out [B,Tq,H,D] normalized,
    lse [B,H,Tq]). With m = max(lse_a, lse_b) and w_x = exp(lse_x - m):
    out = (w_a*out_a + w_b*out_b) / (w_a + w_b), lse = m + log(w_a+w_b)
    — associative, so it carries through a lax.scan (the ring).
    Fully-masked blocks arrive with lse = _NEG_INF and weight 0; rows
    masked in BOTH emit zeros (the l == 0 convention of
    tpunet/ops/attention.py)."""
    oa, la = state_a
    ob, lb = state_b
    m = jnp.maximum(la, lb)                        # [B, H, Tq]
    # Guard exp(_NEG_INF - _NEG_INF) = 1 on rows masked in both.
    both_dead = m <= _NEG_INF
    wa = jnp.where(both_dead, 0.0, jnp.exp(la - m))
    wb = jnp.where(both_dead, 0.0, jnp.exp(lb - m))
    denom = wa + wb
    safe = jnp.where(denom == 0.0, 1.0, denom)
    to_bthd = lambda w: w.transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    out = (to_bthd(wa) * oa.astype(jnp.float32)
           + to_bthd(wb) * ob.astype(jnp.float32)) / to_bthd(safe)
    lse = jnp.where(denom == 0.0, _NEG_INF, m + jnp.log(safe))
    return out.astype(oa.dtype), lse


def _entry(prim, seg_prim, q, k, v, causal, scale, block_q, block_k,
           interpret, segment_ids=None):
    """Shared entry prologue for both public wrappers: scale default,
    degenerate-length dense fallback, off-TPU/interpret resolution,
    and routing to the fixed-arity segmented primitive when packed-
    sequence segment ids are given."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    tq, tk = q.shape[1], k.shape[1]
    bq = _divisor_block(tq, block_q)
    bk = _divisor_block(tk, block_k)
    dense = functools.partial(dense_attention, q, k, v, causal=causal,
                              scale=scale, segment_ids=segment_ids)
    if (bq < 64 and bq < min(block_q, tq)) or \
            (bk < 64 and bk < min(block_k, tk)):
        # Degenerate lengths (primes etc.) whose only divisors are tiny:
        # a grid of near-1-row blocks would serialize the contraction —
        # fall back to one dense pass instead, the same policy as
        # attention.py's _auto_block. (An explicitly requested small
        # block is honored: tests drive the kernel with block 16/32.)
        return dense()
    if interpret is None:
        if os.environ.get("TPUNET_FLASH_INTERPRET",
                          "").lower() not in ("", "0", "false"):
            # Force the Pallas interpreter off-TPU (driver dryrun/tests:
            # exercises the real kernel body, not the dense fallback).
            interpret = True
        elif jax.default_backend() != "tpu":
            return dense()
        else:
            interpret = False
    if segment_ids is not None:
        qseg = jnp.asarray(segment_ids[0], jnp.int32)
        kseg = jnp.asarray(segment_ids[1], jnp.int32)
        return seg_prim(q, k, v, qseg, kseg, causal, scale, block_q,
                        block_k, interpret)
    return prim(q, k, v, causal, scale, block_q, block_k, interpret)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512,
                    block_k: int = 512,
                    interpret: Optional[bool] = None,
                    segment_ids=None) -> jax.Array:
    """Fused flash attention, BTHD layout, drop-in for dense_attention.

    On TPU the Pallas kernel runs; off-TPU the default is the XLA dense
    reference (pass ``interpret=True`` to exercise the kernel in tests).
    Blocks clamp to the largest divisor of the sequence length <= the
    requested size, so any length works (degenerate lengths fall back
    to a dense pass). ``segment_ids``: optional (q_seg [B,Tq],
    kv_seg [B,Tk]) int pair for packed-sequence masking — a query
    attends only to keys with the same segment id (compose with
    ``causal`` for packed causal LM training; padding gets a dedicated
    id so real tokens never attend to it).
    """
    return _entry(_flash, _flash_seg, q, k, v, causal, scale, block_q,
                  block_k, interpret, segment_ids=segment_ids)
