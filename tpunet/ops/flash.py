"""Pallas TPU kernel: flash attention (fused online-softmax attention).

The pure-JAX attention family (tpunet/ops/attention.py) bounds MEMORY
via lax.scan online softmax, but XLA still materializes each [bq, Tk]
score block in HBM between the two einsums. This kernel fuses
scores -> online softmax -> weighted values into one VMEM-resident
program per (batch, head, q-block): scores never leave VMEM, the two
matmuls hit the MXU back-to-back, and the running (m, l, acc) state
lives in scratch that persists across the sequential k-block grid axis
(the standard TPU FlashAttention schedule).

Design notes:
- Grid (B, H, nq, nk); TPU iterates the LAST axis sequentially on one
  core, so VMEM scratch carries the online-softmax state across k
  blocks; @pl.when(k==0) initializes, @pl.when(k==nk-1) finalizes.
- m/l scratch is (bq, 128): Mosaic wants the lane dim, values are
  broadcast across it and read back as [:, :1].
- Causal masking uses the same "explicitly zero masked probabilities"
  convention as tpunet/ops/attention.py (fully-masked rows emit zeros,
  not uniform attention).
- float32 accumulation regardless of compute dtype (MXU-native bf16 in,
  f32 out of the dot).
- Backward: jax.custom_vjp whose bwd re-runs the BLOCKWISE reference
  through jax.vjp — O(T x block) memory and bit-agreement with the
  tested pure-JAX math; writing the flash backward kernel is the next
  optimization, not a correctness need.
- Off-TPU the public entry falls back to dense_attention (the Pallas
  interpreter is far too slow for a hot path); tests exercise the real
  kernel body on CPU with interpret=True, the same scheme as
  tpunet/ops/depthwise.py.

Measured on a real TPU v5e chip (B=4, T=4096, H=8, D=64, causal,
bfloat16; synchronized by fetching a data-dependent output element):
flash 13.0 ms/call vs dense 25.6 ms vs blockwise 17.1 ms — 1.97x over
XLA's dense emitter, 1.31x over the scan-based blockwise path, forward
only (the backward is the blockwise reference either way). Of that,
the causal block-skip (@pl.when around both dots for fully-future k
blocks) is worth ~8% (skipped blocks still pay their grid step and k/v
block copies — restricting the grid itself is the next step) and
keeping the dots in bf16 another ~4%.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpunet.ops.attention import (_NEG_INF, _divisor_block,
                                  blockwise_attention, dense_attention)


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
            scale: float, causal: bool, bq: int, bk: int, nk: int,
            tq: int, tk: int):
    qi = pl.program_id(2)     # program ids are hoisted out of the
    ki = pl.program_id(3)     # pl.when bodies (cond sub-traces cannot
                              # bind pallas primitives in interpret mode)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: skip BOTH MXU dots for k blocks that lie entirely in the
    # future of this q block (they would only add zeros) — for tq == tk
    # self-attention that is ~half of all grid steps.
    if causal:
        needed = (qi + 1) * bq - 1 + (tk - tq) >= ki * bk
    else:
        needed = True

    @pl.when(needed)
    def _compute():
        # Dots run in the INPUT dtype with f32 accumulation (bf16 MXU
        # throughput; attention.py's einsums use the same convention).
        q = q_ref[0, 0]                            # [bq, D]
        k = k_ref[0, 0]                            # [bk, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = None
        if causal:
            # Global positions; the tk - tq offset matches
            # dense_attention's convention for decode windows.
            qpos = (qi * bq
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0))
            kpos = (ki * bk
                    + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1))
            mask = qpos + (tk - tq) >= kpos
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # [bq, 1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                     # [bq, bk]
        if mask is not None:
            # Fully-masked ROWS keep m at the init floor; exp(s - m)
            # there is 1, so zero the masked probabilities explicitly
            # (same convention as attention.py's _block_update).
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)             # [bq, 1]
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _pallas_forward(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool, scale: float,
                    block_q: int, block_k: int,
                    interpret: bool) -> jax.Array:
    """q [B,Tq,H,D], k/v [B,Tk,H,D] -> [B,Tq,H,D]."""
    from jax.experimental.pallas import tpu as pltpu

    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _divisor_block(tq, block_q)
    bk = _divisor_block(tk, block_k)
    nq, nk = tq // bq, tk // bk

    qt = q.swapaxes(1, 2)                          # [B, H, Tq, D]
    kt = k.swapaxes(1, 2)
    vt = v.swapaxes(1, 2)
    kern = functools.partial(_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk, nk=nk, tq=tq, tk=tk)
    out = pl.pallas_call(
        kern,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),    # running max m
            pltpu.VMEM((bq, 128), jnp.float32),    # running normalizer l
            pltpu.VMEM((bq, d), jnp.float32),      # un-normalized acc
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.swapaxes(1, 2)                      # back to BTHD


# ---------------------------------------------------------------------------
# SPMD partitioning: a pallas_call is opaque to GSPMD, so without a rule
# the partitioner would all-gather the sharded batch onto every device
# (the same issue tpunet/ops/depthwise.py solves). Flash attention is
# trivially parallel over batch and heads (the grid's first two axes);
# seq and head_dim must stay replicated per shard.
# ---------------------------------------------------------------------------

from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P


def _flash_spec(arg_shapes) -> P:
    sh = arg_shapes[0].sharding
    qs = list(sh.spec) if isinstance(sh, NamedSharding) else []
    qs += [None] * (4 - len(qs))
    return P(qs[0], None, qs[2], None)   # batch/head shardable


def _infer(causal, scale, block_q, block_k, interpret, mesh, arg_shapes,
           result_shape):
    return NamedSharding(mesh, _flash_spec(arg_shapes))


def _partition(causal, scale, block_q, block_k, interpret, mesh,
               arg_shapes, result_shape):
    spec = _flash_spec(arg_shapes)
    sharding = NamedSharding(mesh, spec)

    def lower_fn(q, k, v):
        return _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                               interpret)

    return mesh, lower_fn, sharding, (sharding,) * 3


_partitioned = custom_partitioning(_pallas_forward,
                                   static_argnums=(3, 4, 5, 6, 7))
_partitioned.def_partition(
    partition=_partition,
    infer_sharding_from_operands=_infer,
    sharding_rule="b tq h d, b tk h d, b tk h d -> b tq h d",
    # Shardy wants these sorted by factor introduction order
    # (b, tq, h, d from q, then tk from k).
    need_replication_factors=("tq", "d", "tk"),
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _partitioned(q, k, v, causal, scale, block_q, block_k,
                        interpret)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash(q, k, v, causal, scale, block_q, block_k,
                  interpret), (q, k, v)


# Shard-local variant: the same kernel WITHOUT the custom_partitioning
# wrapper, for callers already inside shard_map (e.g. the Ulysses
# sequence-parallel core) where every array is per-shard and GSPMD has
# nothing left to partition.
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_local(q, k, v, causal, scale, block_q, block_k, interpret):
    return _pallas_forward(q, k, v, causal, scale, block_q, block_k,
                           interpret)


def _fwd_local(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_local(q, k, v, causal, scale, block_q, block_k,
                        interpret), (q, k, v)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    # Blockwise reference backward: O(T x block) memory, exactly the
    # tested pure-JAX math (attention.py). A flash backward kernel is
    # future perf work, not a correctness requirement.
    q, k, v = res
    bk = _divisor_block(k.shape[1], block_k)
    _, vjp = jax.vjp(
        lambda qq, kk, vv: blockwise_attention(
            qq, kk, vv, block_size=bk, causal=causal, scale=scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_fwd, _bwd)
_flash_local.defvjp(_fwd_local, _bwd)  # same residuals/backward math


def local_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          causal: bool = False,
                          scale: Optional[float] = None,
                          block_q: int = 512,
                          block_k: int = 512,
                          interpret: Optional[bool] = None) -> jax.Array:
    """flash_attention for use INSIDE shard_map bodies: per-shard
    arrays, no custom_partitioning wrapper. Same fallbacks (dense for
    degenerate lengths; dense off-TPU unless interpret=True)."""
    return _entry(_flash_local, q, k, v, causal, scale, block_q, block_k,
                  interpret)


def _entry(prim, q, k, v, causal, scale, block_q, block_k, interpret):
    """Shared entry prologue for both public wrappers: scale default,
    degenerate-length dense fallback, off-TPU/interpret resolution."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    tq, tk = q.shape[1], k.shape[1]
    bq = _divisor_block(tq, block_q)
    bk = _divisor_block(tk, block_k)
    if (bq < 64 and bq < min(block_q, tq)) or \
            (bk < 64 and bk < min(block_k, tk)):
        # Degenerate lengths (primes etc.) whose only divisors are tiny:
        # a grid of near-1-row blocks would serialize the contraction —
        # fall back to one dense pass instead, the same policy as
        # attention.py's _auto_block. (An explicitly requested small
        # block is honored: tests drive the kernel with block 16/32.)
        return dense_attention(q, k, v, causal=causal, scale=scale)
    if interpret is None:
        if jax.default_backend() != "tpu":
            return dense_attention(q, k, v, causal=causal, scale=scale)
        interpret = False
    return prim(q, k, v, causal, scale, block_q, block_k, interpret)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 512,
                    block_k: int = 512,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused flash attention, BTHD layout, drop-in for dense_attention.

    On TPU the Pallas kernel runs; off-TPU the default is the XLA dense
    reference (pass ``interpret=True`` to exercise the kernel in tests).
    Blocks clamp to the largest divisor of the sequence length <= the
    requested size, so any length works (degenerate lengths fall back
    to a dense pass).
    """
    return _entry(_flash, q, k, v, causal, scale, block_q, block_k,
                  interpret)
