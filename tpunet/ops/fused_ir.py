"""Fused inverted-residual 1x1 Pallas kernel pair (conv + BN-stats + ReLU6).

The MobileNetV2 train step is HBM-bound (docs/performance.md): round 5
decomposed the remaining 2x roofline gap into ~1.55x excess traffic,
naming the training-BN second pass and the backward's activation
re-reads as the sources. This module attacks both for the expand and
project 1x1 convolutions that bracket the depthwise kernel
(tpunet/ops/depthwise.py) in every inverted-residual block:

- **Forward** (``_fwd_kernel``): one VMEM pass computes the 1x1 conv
  (an MXU matmul over channels — no halo, no padding) AND the per-image
  batch-statistic partials (sum and sum-of-squares per channel, reduced
  from the *cast* conv output so statistics match the unfused path's
  bf16-resident input). The training-BN statistics pass — a full HBM
  read of the conv output in the unfused schedule — never happens; XLA
  finishes the (C,)-sized cross-image reduction and applies the
  normalize/scale/shift/clamp epilogue in one further fused
  read+write. Net: one whole activation read removed per 1x1 conv.
- **Backward** (``_bwd_kernel``): the cheap elementwise epilogue
  (ReLU6 mask, y-hat, the BN-backward recombination) is *recomputed in
  VMEM* from the saved conv output instead of materializing the
  conv-input cotangent to HBM: one stripe pass reads (g, y, x), builds
  t = d(loss)/d(conv_out) on-chip, computes dx = t @ w^T on the MXU,
  and reduces the per-image dw partial [Cin, Cout] in f32 in the same
  pass. The unfused schedule's materialized cotangent (one write + two
  conv-backward reads) never exists in HBM. dw partials are summed
  over batch OUTSIDE the kernel so data-parallel batch partitioning
  stays a plain psum XLA inserts from shardings (the same contract as
  the depthwise backward). The (C,)-sized BN-backward reductions
  (sum g*mask, sum g*mask*y_hat) are a cheap XLA prelude — they must
  complete over the whole batch before any stripe's t is computable,
  so they cannot live inside the sequential grid.

Per-shape dispatch (``_kernel_pays``): the per-image dw partial costs
``Cin*Cout*4`` bytes against the ``~3*H*W*Cout*2`` bytes of saved
epilogue traffic, so the pair pays (with margin) when ``Cin < H*W``.
At 224px input that engages 20 of the 33 expand/project convs — every
expand at 112..14px spatial and every project through 28px; the
fat-input 14px projects (Cin 384..576 vs H*W = 196), the 7px tail,
and the 320->1280 head keep the XLA path — the same honest per-shape
verdict discipline as the round-4 depthwise-forward result
(docs/performance.md). Off-TPU the reference runs (the interpreter is
far too slow for a hot path); ``interpret=True`` exercises both
kernels in tests; ``TPUNET_FUSED_IR_REF=1`` is the escape hatch back
to the XLA reference on TPU (e.g. a Mosaic regression on a new
toolchain) without touching checkpoints or configs.

The reference path (``conv1x1_bn_act_reference``) mirrors
``models.mobilenetv2.FusedBNAct`` op for op, so flipping
``ModelConfig.fused_ir`` changes nothing numerically on backends where
the kernels don't engage, and eval mode (which never calls this
module) stays bit-identical by construction.

Contract notes: the ``(out, mean, var)`` outputs' ``mean``/``var`` are
auxiliary (they feed the module's running-stat update, which flax does
not differentiate); the custom backward treats their cotangents as
zero. Parity is property-tested against ``jax.vjp`` of the reference
composition in interpret mode on CPU (tests/test_fused_ir.py).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

from tpunet.compat import def_partition_compat


# ---------------------------------------------------------------------------
# Reference (XLA) path: op-for-op the nn.Conv(1x1) -> FusedBNAct schedule
# of models/mobilenetv2.py, so fused_ir on/off is numerically identical
# wherever the kernels don't engage.
# ---------------------------------------------------------------------------


def conv1x1_reference(x: jax.Array, w: jax.Array) -> jax.Array:
    """x [N,H,W,Ci] @ w [Ci,Co] as the conv nn.Conv emits (bit-compatible
    with the unfused module path)."""
    return jax.lax.conv_general_dilated(
        x, w[None, None], window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def conv1x1_bn_act_reference(x: jax.Array, w: jax.Array, scale: jax.Array,
                             bias: jax.Array, act: bool,
                             eps: float) -> Tuple[jax.Array, jax.Array,
                                                  jax.Array]:
    """-> (out, batch_mean, batch_var); the exact FusedBNAct train math."""
    y = conv1x1_reference(x, w)
    y = checkpoint_name(y, "tpunet_convout")
    axes = tuple(range(y.ndim - 1))
    yf = y.astype(jnp.float32)
    mean = jnp.mean(yf, axes)
    var = jnp.maximum(0.0, jnp.mean(yf * yf, axes) - mean * mean)
    # Named for the block-remat saved-residual policy (same contract
    # as FusedBNAct): the (C,)-sized stats are kept so the replay
    # never re-reduces the full conv output.
    mean = checkpoint_name(mean, "tpunet_bn_stats")
    var = checkpoint_name(var, "tpunet_bn_stats")
    inv = jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    shift = bias.astype(jnp.float32) - mean * inv
    o = yf * inv + shift
    if act:
        o = jnp.minimum(jnp.maximum(o, 0.0), 6.0)  # ReLU6
    return o.astype(y.dtype), mean, var


# ---------------------------------------------------------------------------
# Forward kernel: y = x @ w and the per-image (sum, sum-of-squares)
# stat partials in one stripe pass.
# ---------------------------------------------------------------------------


def _pick_rows(h: int, w: int, ci: int, co: int, bufs_ci: int,
               bufs_co: int) -> int:
    """Largest divisor of ``h`` whose stripe temporaries (f32-equivalent
    buffer counts per element: ``bufs_ci`` input-channel-sized,
    ``bufs_co`` output-channel-sized) stay within a ~4 MB budget —
    the same scoped-vmem discipline as the depthwise kernel's
    ``_pick_rows`` (whole-image programs overflow the 16 MB stack at
    the 112px layers)."""
    budget = 4 * 1024 * 1024
    for rows in range(h, 0, -1):
        if h % rows == 0 and \
                rows * w * (bufs_ci * ci + bufs_co * co) * 4 <= budget:
            return rows
    return 1


def _fwd_kernel(x_ref, w_ref, y_ref, p_ref):
    """One output-row stripe per grid step. The stat partials reduce
    the *cast* conv output (matching the unfused path, whose BN reads
    the bf16-resident activation) and accumulate into the per-image
    (2, Co) block across stripes (j == 0 initializes — the standard
    TPU revisiting pattern; the grid is sequential per image)."""
    xs = x_ref[0]                                   # (rows, W, Ci)
    rows, wdt, _ = xs.shape
    yf = jnp.dot(xs.reshape(rows * wdt, -1), w_ref[:],
                 preferred_element_type=jnp.float32)
    yc = yf.astype(y_ref.dtype)
    y_ref[0] = yc.reshape(rows, wdt, -1)
    yb = yc.astype(jnp.float32)
    part = jnp.stack([jnp.sum(yb, axis=0),
                      jnp.sum(yb * yb, axis=0)])    # (2, Co)

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        p_ref[0] = part

    @pl.when(j > 0)
    def _accum():
        p_ref[0] = p_ref[0] + part


def _pallas_forward(x: jax.Array, w: jax.Array, interpret: bool):
    """(x [N,H,W,Ci], w [Ci,Co]) -> (y [N,H,W,Co] x.dtype,
    partials [N,2,Co] f32)."""
    n, h, wdt, ci = x.shape
    co = w.shape[-1]
    rows = _pick_rows(h, wdt, ci, co, bufs_ci=2, bufs_co=6)
    return pl.pallas_call(
        _fwd_kernel,
        grid=(n, h // rows),
        in_specs=[
            pl.BlockSpec((1, rows, wdt, ci), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((ci, co), lambda i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, rows, wdt, co), lambda i, j: (i, j, 0, 0)),
            # Constant over j: resident, accumulates across stripes.
            pl.BlockSpec((1, 2, co), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wdt, co), x.dtype),
            jax.ShapeDtypeStruct((n, 2, co), jnp.float32),
        ],
        interpret=interpret,
    )(x, w)


# SPMD: the op is trivially parallel over batch (the kernel grids over
# N); H/W/channels stay replicated (Ci is contracted, Co would need w
# sharded). Without a rule the partitioner would all-gather the batch.


def _batch_spec(arg_shapes):
    def spec_of(s):
        sh = s.sharding
        return sh.spec if isinstance(sh, NamedSharding) else P()
    xs = list(spec_of(arg_shapes[0])) + [None] * 4
    return P(xs[0], None, None, None)


def _fwd_infer(interpret, mesh, arg_shapes, result_shape):
    b = _batch_spec(arg_shapes)[0]
    return (NamedSharding(mesh, P(b, None, None, None)),
            NamedSharding(mesh, P(b, None, None)))


def _fwd_partition(interpret, mesh, arg_shapes, result_shape):
    b = _batch_spec(arg_shapes)[0]
    arg_shardings = (NamedSharding(mesh, P(b, None, None, None)),
                     NamedSharding(mesh, P(None, None)))
    result_shardings = (NamedSharding(mesh, P(b, None, None, None)),
                        NamedSharding(mesh, P(b, None, None)))

    def lower_fn(x, w):
        return _pallas_forward(x, w, interpret)

    return mesh, lower_fn, result_shardings, arg_shardings


_partitioned_fwd = custom_partitioning(_pallas_forward, static_argnums=(2,))
def_partition_compat(
    _partitioned_fwd,
    partition=_fwd_partition,
    infer_sharding_from_operands=_fwd_infer,
    sharding_rule="n h w ci, ci co -> n h w co, n stat co",
    need_replication_factors=("h", "w", "ci", "co", "stat"),
)


# ---------------------------------------------------------------------------
# Backward kernel: recompute the elementwise epilogue in VMEM, fuse
# dx = t @ w^T and the per-image dw partial into the same stripe pass.
#
# Math (per channel, n = N*H*W, r = rsqrt(var+eps), yh = (y-mean)*r,
# inv = r*scale, shift = bias - mean*inv, gm = g * relu6_mask):
#   t  = inv * (gm - sum(gm)/n - yh * sum(gm*yh)/n)   # d loss / d y
#   dx = t @ w^T          dw = sum_n x^T t
#   dscale = sum(gm*yh)   dbias = sum(gm)
# The two batch reductions are the XLA prelude; everything per-element
# lives in the kernel, and t never hits HBM.
# ---------------------------------------------------------------------------


def _bwd_kernel(x_ref, g_ref, y_ref, w_ref, c_ref, dx_ref, dwp_ref, *,
                act: bool):
    xs = x_ref[0]                                   # (rows, W, Ci)
    gs = g_ref[0].astype(jnp.float32)               # (rows, W, Co)
    ys = y_ref[0].astype(jnp.float32)
    rows, wdt, ci = xs.shape
    co = gs.shape[-1]
    cf = c_ref[:]                                   # (6, Co) f32
    inv, shift, r, mr, e, f = (cf[0], cf[1], cf[2], cf[3], cf[4], cf[5])
    if act:
        yn = ys * inv + shift                       # pre-clamp activation
        gm = gs * ((yn > 0.0) & (yn < 6.0)).astype(jnp.float32)
    else:
        gm = gs
    yh = ys * r - mr                                # y-hat
    t = (inv * (gm - e - yh * f)).reshape(rows * wdt, co)
    dxs = jax.lax.dot_general(
        t, w_ref[:].astype(jnp.float32),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    dx_ref[0] = dxs.reshape(rows, wdt, ci).astype(dx_ref.dtype)
    part = jax.lax.dot_general(
        xs.reshape(rows * wdt, ci).astype(jnp.float32), t,
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dwp_ref[0] = part

    @pl.when(j > 0)
    def _accum():
        dwp_ref[0] = dwp_ref[0] + part


def _pallas_backward(x: jax.Array, g: jax.Array, y: jax.Array,
                     w: jax.Array, chan: jax.Array, act: bool,
                     interpret: bool):
    """-> (dx [N,H,W,Ci] x.dtype, per-image dw partials [N,Ci,Co] f32)."""
    n, h, wdt, ci = x.shape
    co = w.shape[-1]
    rows = _pick_rows(h, wdt, ci, co, bufs_ci=3, bufs_co=8)
    kern = functools.partial(_bwd_kernel, act=act)
    return pl.pallas_call(
        kern,
        grid=(n, h // rows),
        in_specs=[
            pl.BlockSpec((1, rows, wdt, ci), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, rows, wdt, co), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, rows, wdt, co), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((ci, co), lambda i, j: (0, 0)),
            pl.BlockSpec((6, co), lambda i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, rows, wdt, ci), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, ci, co), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h, wdt, ci), x.dtype),
            jax.ShapeDtypeStruct((n, ci, co), jnp.float32),
        ],
        interpret=interpret,
    )(x, g, y, w, chan)


def _bwd_infer(act, interpret, mesh, arg_shapes, result_shape):
    b = _batch_spec(arg_shapes)[0]
    return (NamedSharding(mesh, P(b, None, None, None)),
            NamedSharding(mesh, P(b, None, None)))


def _bwd_partition(act, interpret, mesh, arg_shapes, result_shape):
    b = _batch_spec(arg_shapes)[0]
    batched = NamedSharding(mesh, P(b, None, None, None))
    repl2 = NamedSharding(mesh, P(None, None))
    arg_shardings = (batched, batched, batched, repl2, repl2)
    result_shardings = (batched, NamedSharding(mesh, P(b, None, None)))

    def lower_fn(x, g, y, w, chan):
        return _pallas_backward(x, g, y, w, chan, act, interpret)

    return mesh, lower_fn, result_shardings, arg_shardings


_partitioned_bwd = custom_partitioning(_pallas_backward,
                                       static_argnums=(5, 6))
def_partition_compat(
    _partitioned_bwd,
    partition=_bwd_partition,
    infer_sharding_from_operands=_bwd_infer,
    sharding_rule=("n h w ci, n h w co, n h w co, ci co, six co "
                   "-> n h w ci, n ci co"),
    need_replication_factors=("h", "w", "ci", "co", "six"),
)


# ---------------------------------------------------------------------------
# custom_vjp over the kernel path. Only shapes the kernel pays for enter
# this function (dispatch below), so the backward never needs a
# re-run-the-forward reference fallback.
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused(x, w, scale, bias, act, eps, interpret):
    out, _mean, _var, _y, *_ = _fused_fwd_impl(x, w, scale, bias, act,
                                               eps, interpret)
    return out, _mean, _var


def _fused_fwd_impl(x, w, scale, bias, act, eps, interpret):
    with jax.named_scope("tpunet_fused_ir_fwd"):
        y, part = _partitioned_fwd(x, w, interpret)
    # The conv output is the residual the backward reads — name it for
    # the block-remat saved-residual policy (models/mobilenetv2.py).
    y = checkpoint_name(y, "tpunet_convout")
    n = x.shape[0] * x.shape[1] * x.shape[2]
    s = jnp.sum(part, axis=0)           # plain psum under batch sharding
    mean = s[0] / n
    var = jnp.maximum(0.0, s[1] / n - mean * mean)
    # Saved-residual names survive the custom_vjp boundary, so the
    # block-remat policy keeps the (C,)-sized stats here too.
    mean = checkpoint_name(mean, "tpunet_bn_stats")
    var = checkpoint_name(var, "tpunet_bn_stats")
    r = jax.lax.rsqrt(var + eps)
    inv = r * scale.astype(jnp.float32)
    shift = bias.astype(jnp.float32) - mean * inv
    o = y.astype(jnp.float32) * inv + shift
    if act:
        o = jnp.minimum(jnp.maximum(o, 0.0), 6.0)
    return o.astype(y.dtype), mean, var, y, inv, shift, r, mean * r


def _fused_fwd(x, w, scale, bias, act, eps, interpret):
    out, mean, var, y, inv, shift, r, mr = _fused_fwd_impl(
        x, w, scale, bias, act, eps, interpret)
    res = (x, w, scale, bias, y, inv, shift, r, mr)
    return (out, mean, var), res


def _fused_bwd(act, eps, interpret, res, cts):
    # cts = (g_out, g_mean, g_var); the stats outputs feed only the
    # (non-differentiated) running-stat update, so their cotangents are
    # treated as zero — the documented contract of this op.
    #
    # The ENTIRE body sits under the tpunet_fused_ir_bwd scope: a
    # custom_vjp backward carries no ``transpose(`` marker, so the
    # scope is what keeps the prelude's full-tensor g/y reads and the
    # dw batch-sum attributed to the backward phase / conv_bwd bucket
    # (tpunet/obs/hlo_bytes.py) instead of leaking into fwd.
    with jax.named_scope("tpunet_fused_ir_bwd"):
        x, w, scale, bias, y, inv, shift, r, mr = res
        g = cts[0]
        n = x.shape[0] * x.shape[1] * x.shape[2]
        axes = tuple(range(y.ndim - 1))
        yf = y.astype(jnp.float32)
        if act:
            yn = yf * inv + shift
            gm = g.astype(jnp.float32) * ((yn > 0.0) & (yn < 6.0)
                                          ).astype(jnp.float32)
        else:
            gm = g.astype(jnp.float32)
        yh = yf * r - mr
        r1 = jnp.sum(gm, axes)              # = dbias
        r2 = jnp.sum(gm * yh, axes)         # = dscale
        chan = jnp.stack([inv, shift, r, mr, r1 / n, r2 / n])
        dx, dwp = _partitioned_bwd(x, g, y, w, chan, act, interpret)
        dw = jnp.sum(dwp, axis=0).astype(w.dtype)   # psum stays in XLA
        return dx, dw, r2.astype(scale.dtype), r1.astype(bias.dtype)


_fused.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# Dispatch.
# ---------------------------------------------------------------------------


def _kernel_pays(shape) -> bool:
    """Per-shape profitability: the backward's per-image dw partial
    costs Ci*Co*4 bytes against ~3*H*W*Co*2 bytes of saved epilogue
    traffic, so the pair pays (with margin) iff Ci < H*W. At 224px
    that is 20/33 expand+project convs — every expand at 112..14px
    and every project through 28px; the fat-input 14px projects
    (Ci 384..576 vs H*W = 196), the 7px tail, and the 320->1280 head
    keep the XLA emitter — a recorded per-shape verdict, like the
    round-4 depthwise-forward result."""
    _, h, w, ci = shape
    return ci < h * w


def use_fused_ir_kernel(shape) -> bool:
    """Would ``conv1x1_bn_act`` run the Pallas pair for this input
    shape on the current backend? (Factored out for tests and for the
    docs' per-shape table.)"""
    if jax.default_backend() != "tpu":
        return False
    if os.environ.get("TPUNET_FUSED_IR_REF"):
        return False
    return _kernel_pays(shape)


def conv1x1_bn_act(x: jax.Array, w: jax.Array, scale: jax.Array,
                   bias: jax.Array, act: bool = True, eps: float = 1e-5,
                   interpret: Optional[bool] = None
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused train-mode 1x1-conv + BatchNorm-stats + scale/shift
    (+ReLU6): -> (out, batch_mean, batch_var).

    ``x`` [N,H,W,Ci], ``w`` [Ci,Co]; ``scale``/``bias`` are the BN
    affine params. On TPU, shapes passing ``_kernel_pays`` run the
    Pallas kernel pair under ``jax.custom_vjp``; everything else (and
    every other backend, and ``TPUNET_FUSED_IR_REF=1``) runs the XLA
    reference, whose ops mirror the unfused module path exactly — so
    the flag flips freely on existing checkpoints. ``interpret=True``
    forces the kernels through the Pallas interpreter (tests).

    The ``mean``/``var`` outputs are auxiliary (running-stat updates):
    their cotangents are treated as zero by the custom backward.
    """
    if interpret is None:
        if not use_fused_ir_kernel(x.shape):
            return conv1x1_bn_act_reference(x, w, scale, bias, act, eps)
        interpret = False
    return _fused(x, w, scale, bias, act, eps, interpret)
