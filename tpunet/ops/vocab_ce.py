"""Vocab-sharded (Megatron-style) cross-entropy for the LM families.

The reference computes its CE on full logits (a 10-class CNN —
cifar10_mpi_mobilenet_224.py:157 nn.CrossEntropyLoss — where that is
free); tpunet's LMs tie the output projection to the embedding and at
real vocabularies the [B, T, V] float32 logits tensor is the single
largest array in the train step — at V=32k, B=8, T=2048 it is 2.1 GB,
dwarfing the activation memory the 1F1B pipeline executor saves. This
op never materializes it: the final hidden states enter a shard_map
over ('data', 'model'), each device computes logits against only its
VOCAB SLICE of the (tied) embedding — [B/dp, T, V/vp] — and the
softmax statistics are assembled with three tiny collectives over
'model' (pmax of the row max, psum of the exp-sum, psum of the
target's logit), the standard max-subtract log-sum-exp factorization:

    ce = lse - tgt_logit,
    lse = m + log(psum_v sum exp(logits_v - m)),  m = pmax_v max logits_v

Peak logits memory drops vp-fold (measured via XLA memory analysis in
tests/test_vocab_ce.py); comm cost is O(B*T) scalars per collective —
independent of V — plus nothing else: the embedding table stays
REPLICATED in storage (at [V, C] it is ~1000x smaller than the logits
it replaces; each shard_map body slices its vocab rows locally for
free), so checkpoints, serving and the input lookup are untouched.

Gradients flow through the same factorization (the row max is
stop-gradient'd — analytically it cancels from lse, so this changes
nothing but removes the pmax from the backward): shard_map AD psums
the hidden-state cotangent over 'model' and concatenates the per-slice
embedding cotangents, giving 1e-6-level parity with the full-logits
path (asserted in tests/test_vocab_ce.py).

Accuracy under sharding: ``hit`` is ``tgt_logit >= global_max`` —
identical to ``argmax == target`` except when the max is achieved by
several classes at once (then argmax's first-index tie-break may miss
the target while hit counts it). Ties on float32 LM logits are
measure-zero; documented deviation.

The model-side hook is ``return_hidden=True`` on TransformerLM /
PipelinedLM (the final-LN hidden states instead of logits); the train
and eval steps wire it when ``--vocab-ce`` resolves to "sharded"
(tpunet/train/steps.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpunet.compat import shard_map


def resolve_vocab_ce(vocab_ce: str, mesh, vocab_size: int) -> str:
    """Resolve a ``--vocab-ce`` setting: "auto" prefers "sharded"
    whenever the mesh has a 'model' axis > 1 that divides the vocab,
    else "full"; explicit "sharded" raises where auto falls back."""
    if vocab_ce not in ("auto", "sharded", "full"):
        raise ValueError(f"unknown vocab_ce {vocab_ce!r}; "
                         "expected auto|sharded|full")
    vp = mesh.shape.get("model", 1) if mesh is not None else 1
    ok = vp > 1 and vocab_size % vp == 0
    if vocab_ce == "sharded" and not ok:
        raise ValueError(
            f"vocab_ce='sharded' needs a mesh 'model' axis > 1 that "
            f"divides the vocab ({vocab_size}); have "
            f"{'no mesh' if mesh is None else f'model={vp}'}")
    if vocab_ce == "full":
        return "full"
    return "sharded" if ok else "full"


@jax.custom_vjp
def _pmax_model_const(x):
    """pmax over 'model' with a zero vjp: the row max is a numerical
    shift that cancels analytically from the log-sum-exp, so its true
    cotangent contribution is zero — and jax.lax.pmax has no
    differentiation rule to say so itself."""
    return jax.lax.pmax(x, "model")


def _pmax_fwd(x):
    return _pmax_model_const(x), None


def _pmax_bwd(_, ct):
    return (jnp.zeros_like(ct),)


_pmax_model_const.defvjp(_pmax_fwd, _pmax_bwd)


def vocab_parallel_ce(h, emb, targets, mesh, *, smoothing: float = 0.0):
    """Per-token CE and argmax-hit from hidden states, vocab-sharded.

    h [B, T, C] (any float dtype; cast to float32), emb [V, C] (the
    tied embedding, replicated), targets [B, T] int32. Returns
    (ce [B, T] float32, hit [B, T] float32) — exactly
    ``optax.softmax_cross_entropy*(h @ emb.T, targets)`` and
    ``argmax(h @ emb.T) == targets`` (up to ties), with per-device
    logits bounded at [B/dp, T, V/vp]. ``smoothing`` matches
    optax.smooth_labels semantics: the smoothed CE is
    ``lse - ((1-s)*tgt_logit + (s/V)*sum_logits)``."""
    v, _ = emb.shape
    vp = mesh.shape["model"]
    if v % vp:
        raise ValueError(f"vocab {v} not divisible by the mesh "
                         f"'model' axis ({vp})")
    b = h.shape[0]
    dp = mesh.shape.get("data", 1)
    if b % dp:
        raise ValueError(f"batch {b} not divisible by the mesh "
                         f"'data' axis ({dp})")

    def body(h_l, emb_l, tgt_l):
        v_l = emb_l.shape[0]
        logits = jnp.einsum("btc,vc->btv", h_l.astype(jnp.float32),
                            emb_l.astype(jnp.float32))   # [b_l, T, v_l]
        # Row max over the FULL vocab (zero-vjp pmax: it cancels
        # analytically from lse, see _pmax_model_const).
        m = _pmax_model_const(jnp.max(logits, -1))       # [b_l, T]
        z = jax.lax.psum(
            jnp.sum(jnp.exp(logits - m[..., None]), -1), "model")
        lse = m + jnp.log(z)
        off = jax.lax.axis_index("model") * v_l
        loc = jnp.clip(tgt_l - off, 0, v_l - 1)
        tl = jnp.take_along_axis(logits, loc[..., None], -1)[..., 0]
        mine = ((tgt_l >= off) & (tgt_l < off + v_l)).astype(jnp.float32)
        tgt_logit = jax.lax.psum(tl * mine, "model")
        if smoothing > 0.0:
            mean_logit = jax.lax.psum(jnp.sum(logits, -1), "model") / v
            ce = lse - ((1.0 - smoothing) * tgt_logit
                        + smoothing * mean_logit)
        else:
            ce = lse - tgt_logit
        hit = (tgt_logit >= m).astype(jnp.float32)
        return ce, hit

    tok = P("data", None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P("data", None, None), P("model", None), tok),
        out_specs=(tok, tok), check_vma=False)
    return fn(h, emb, targets)
