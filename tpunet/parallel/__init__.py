from tpunet.parallel.mesh import (  # noqa: F401
    make_mesh, batch_sharding, replicated_sharding, shard_host_batch)
from tpunet.parallel.dist import (  # noqa: F401
    initialize_distributed, process_index, process_count, sync_hosts)
from tpunet.parallel.tp import rules_for, tree_shardings  # noqa: F401
