"""Multi-host bootstrap (replaces mpi4py + torch.distributed rendezvous).

The reference boots with mpirun -> MPI.COMM_WORLD rank discovery
(cifar10_mpi_mobilenet_224.py:24-26) -> env-var TCP rendezvous with a
hardcoded localhost:29500 master (:28-35) -> NCCL process group. The JAX
equivalent is a single :func:`jax.distributed.initialize` call: on TPU
pods the coordinator and process topology come from the platform
metadata, so no addresses are hardcoded; on CPU/GPU clusters they can be
passed explicitly or via standard env vars (JAX_COORDINATOR_ADDRESS,
JAX_NUM_PROCESSES, JAX_PROCESS_ID).

`rank % device_count` device binding (:38-40) has no analogue — JAX owns
local devices automatically. `dist.barrier()` gating the dataset download
(:102) maps to :func:`sync_hosts`.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[str] = None) -> None:
    """Initialize multi-controller JAX when running as part of a pod/cluster.

    Safe to call unconditionally: a no-op for single-process runs unless
    explicit arguments or JAX_* rendezvous env vars are present.
    """
    env = os.environ
    configured = (coordinator_address or num_processes
                  or env.get("JAX_COORDINATOR_ADDRESS")
                  or env.get("JAX_NUM_PROCESSES"))
    # Multi-host TPU pod: TPU_WORKER_HOSTNAMES lists >1 worker. (A
    # single-host TPU VM also sets the variable; initialize() is neither
    # needed nor safe there if the backend was already touched.)
    workers = env.get("TPU_WORKER_HOSTNAMES", "")
    on_tpu_pod = ("," in workers
                  or env.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if not (configured or on_tpu_pod):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_index() -> int:
    """This process's rank (reference `rank`, :25)."""
    return jax.process_index()


def process_count() -> int:
    """World size (reference `world_size`, :26)."""
    return jax.process_count()


def sync_hosts(name: str = "barrier") -> None:
    """Cross-host barrier (reference dist.barrier(), :102)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
