"""Multi-host bootstrap (replaces mpi4py + torch.distributed rendezvous).

The reference boots with mpirun -> MPI.COMM_WORLD rank discovery
(cifar10_mpi_mobilenet_224.py:24-26) -> env-var TCP rendezvous with a
hardcoded localhost:29500 master (:28-35) -> NCCL process group. The JAX
equivalent is a single :func:`jax.distributed.initialize` call: on TPU
pods the coordinator and process topology come from the platform
metadata, so no addresses are hardcoded; on CPU/GPU clusters they can be
passed explicitly or via standard env vars (JAX_COORDINATOR_ADDRESS,
JAX_NUM_PROCESSES, JAX_PROCESS_ID).

`rank % device_count` device binding (:38-40) has no analogue — JAX owns
local devices automatically. `dist.barrier()` gating the dataset download
(:102) maps to :func:`sync_hosts`.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize multi-controller JAX when running as part of a pod/cluster.

    Safe to call unconditionally: a no-op for single-process runs unless
    explicit arguments or JAX_* rendezvous env vars are present.
    """
    env = os.environ
    configured = (coordinator_address or num_processes
                  or env.get("JAX_COORDINATOR_ADDRESS")
                  or env.get("JAX_NUM_PROCESSES"))
    # jax only resolves JAX_COORDINATOR_ADDRESS itself (0.4.x);
    # num_processes/process_id would fall through to cluster
    # auto-detection and fail on a plain CPU gang — resolve the env
    # vars here so the elastic agent's injected world (and the
    # docstring's claim) actually works.
    if num_processes is None and env.get("JAX_NUM_PROCESSES"):
        num_processes = int(env["JAX_NUM_PROCESSES"])
    if process_id is None and env.get("JAX_PROCESS_ID"):
        process_id = int(env["JAX_PROCESS_ID"])
    # Multi-host TPU pod: TPU_WORKER_HOSTNAMES lists >1 worker. (A
    # single-host TPU VM also sets the variable; initialize() is neither
    # needed nor safe there if the backend was already touched.)
    workers = env.get("TPU_WORKER_HOSTNAMES", "")
    on_tpu_pod = ("," in workers
                  or env.get("MEGASCALE_COORDINATOR_ADDRESS"))
    if not (configured or on_tpu_pod):
        return
    if env.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        # CPU gangs (tests, the elastic agent's CPU worlds): jax's
        # cross-process collectives need an explicit implementation —
        # the flag's env var is not consulted at backend init on this
        # jax, so without this every cross-process psum dies with
        # "Multiprocess computations aren't implemented on the CPU
        # backend". gloo ships inside jaxlib; harmless single-process.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass  # older/newer jax without the flag: keep going
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_index() -> int:
    """This process's rank (reference `rank`, :25)."""
    return jax.process_index()


def process_count() -> int:
    """World size (reference `world_size`, :26)."""
    return jax.process_count()


def sync_hosts(name: str = "barrier") -> None:
    """Cross-host barrier (reference dist.barrier(), :102)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


# -- collective-free host agreements -----------------------------------
#
# ``process_allgather`` is an XLA computation: when the MAIN thread
# runs one while the async checkpoint WORKER thread is inside one of
# orbax's cross-host barriers (sync_global_devices), the two
# processes' collective sequences interleave differently and the
# transport aborts (observed on CPU gangs as gloo's
# "op.preamble.length <= op.nbytes" hard abort mid-save). Host-side
# agreements that can overlap async checkpointing therefore go
# through the jax coordination-service KV store instead — plain gRPC
# to the coordinator, no XLA, safe from any thread.


def coordination_client():
    """The jax coordination-service client, or None (single process /
    distributed not initialized)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


_AGREE_TIMEOUT_MS = 300_000


def agree_any(tag: str, flag: bool, *,
              timeout_ms: int = _AGREE_TIMEOUT_MS) -> Optional[bool]:
    """Cross-process OR of a host-side flag (the preemption/evict stop
    agreement) without XLA collectives. ``tag`` must be unique per
    agreement round and identical across processes (e.g. the global
    step). Returns None when no coordination client exists — the
    caller falls back to ``process_allgather`` (which is then safe:
    no coordination service means no multi-controller orbax either).
    """
    client = coordination_client()
    if client is None:
        return None
    base = f"tpunet_agree/{tag}"
    # allow_overwrite: re-agreement on a reused tag (a second trainer
    # incarnation in one process) must be idempotent, not a KV error.
    client.key_value_set(f"{base}/{jax.process_index()}",
                         "1" if flag else "0", allow_overwrite=True)
    client.wait_at_barrier(f"{base}/barrier", timeout_ms)
    return any(
        client.blocking_key_value_get(f"{base}/{i}", timeout_ms) == "1"
        for i in range(jax.process_count()))


def kv_live_processes(tag: str, *,
                      timeout_ms: int = _AGREE_TIMEOUT_MS
                      ) -> Optional[int]:
    """Epoch-heartbeat liveness via the KV store: how many processes
    checked in for this ``tag``. A dead peer surfaces as a barrier
    error -> count whoever did check in (bounded short gets) instead
    of hanging in a device collective. None without a client."""
    client = coordination_client()
    if client is None:
        return None
    base = f"tpunet_hb/{tag}"
    client.key_value_set(f"{base}/{jax.process_index()}", "1",
                         allow_overwrite=True)
    try:
        client.wait_at_barrier(f"{base}/barrier", timeout_ms)
        return jax.process_count()
    except Exception:
        live = 0
        for i in range(jax.process_count()):
            try:
                client.blocking_key_value_get(f"{base}/{i}", 1000)
                live += 1
            except Exception:
                continue
        return live
