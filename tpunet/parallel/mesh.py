"""Device mesh and sharding helpers.

This is the TPU-native replacement for the reference's parallelism stack
(DistributedDataParallel wrap at cifar10_mpi_mobilenet_224.py:142-145 and
the `rank % device_count` device binding at :38-40): instead of one
process per device with bucketed NCCL allreduce hooks, we build a
``jax.sharding.Mesh`` over all devices and jit the train step with the
batch sharded on the ``data`` axis and parameters replicated — XLA then
inserts the gradient all-reduce (over ICI on a TPU slice) itself, fused
into the step program.

The mesh is 4-D ``('data', 'seq', 'pipe', 'model')``: the reference is
DP-only (SURVEY.md section 2b), and the extra axes carry sequence
parallelism (ring attention rotates K/V over 'seq' —
tpunet/ops/attention.py), pipeline parallelism (GPipe microbatches over
'pipe' — tpunet/parallel/pp.py) and tensor/expert-parallel param
sharding (tpunet/parallel/tp.py) without restructuring. Unused axes
have size 1 and cost nothing.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpunet.config import MeshConfig


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    data, seq, pipe, model = cfg.shape(len(devices))
    n = data * seq * pipe * model
    if n > len(devices):
        raise ValueError(f"mesh {data}x{seq}x{pipe}x{model} needs {n} "
                         f"devices, have {len(devices)}")
    if n == len(devices):
        dmesh = mesh_utils.create_device_mesh((data, seq, pipe, model),
                                              devices=devices)
    else:
        dmesh = np.asarray(devices[:n]).reshape(data, seq, pipe, model)
    return Mesh(dmesh, ("data", "seq", "pipe", "model"))


def mesh_shape_dict(mesh: Mesh) -> dict:
    """Axis sizes as a plain dict (``{"data": 2, "seq": 1, ...}``) —
    the ``obs_elastic`` record shape for old/new mesh on grow/shrink
    (docs/metrics_schema.md), and generally the JSON-able mesh
    identity."""
    return {name: int(size) for name, size in mesh.shape.items()}


def elastic_data_axis(cfg: Optional[MeshConfig], n_devices: int) -> int:
    """The data-axis size a (re)formed world of ``n_devices`` yields.

    Elastic grow/shrink resizes ONLY the data axis: seq/pipe/model are
    workload topology (sharded math) while data is throughput — a
    surviving pod keeps the model partitioning and spreads the batch
    over fewer replicas. Raises when the fixed axes no longer fit the
    surviving devices (the agent surfaces this as a quorum-style
    degradation instead of letting jit fail deep in the restore)."""
    cfg = cfg or MeshConfig()
    seq = max(1, cfg.seq)
    pipe = max(1, cfg.pipe)
    model = max(1, cfg.model)
    fixed = seq * pipe * model
    if n_devices < fixed:
        raise ValueError(
            f"surviving world has {n_devices} device(s) but the mesh "
            f"needs seq*pipe*model = {fixed}; the pod cannot shrink "
            "below its model-parallel footprint")
    return max(1, n_devices // fixed)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding over the data axis (DistributedSampler analog)."""
    return NamedSharding(mesh, P(("data",)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Full replication (the reference keeps params replicated, README:77)."""
    return NamedSharding(mesh, P())


def shard_host_batch(mesh: Mesh, *arrays):
    """Assemble global device arrays from this host's shard of the batch.

    Works identically on one host (slices go to local devices) and on a
    multi-host pod (each host contributes its slice of the global batch,
    concatenated in process order).
    """
    sh = batch_sharding(mesh)
    out = tuple(
        jax.make_array_from_process_local_data(sh, np.asarray(a))
        for a in arrays)
    return out if len(out) > 1 else out[0]
