"""Pipeline parallelism: GPipe-style SPMD executor over the 'pipe' axis.

The reference has no pipeline parallelism (single-file model, SURVEY.md
2b); tpunet implements it the TPU way: no per-stage processes, no
send/recv threads — ONE jitted SPMD program in which every device runs
the same code, holds one pipeline stage's worth of stacked layer
parameters (leading dim sharded over 'pipe'), and activations hop
stage-to-stage with ``lax.ppermute`` (one ICI neighbor hop per tick).

Schedule: plain GPipe with M microbatches over S stages; the static
scan runs M + S - 1 ticks. At tick t, stage s computes microbatch
m = t - s (masked out when m is out of range — idle bubble ticks
compute on zeros and are discarded). Stage 0 reads microbatches from
the (replicated) input; stage S-1 accumulates results into the output
buffer, which a final psum over 'pipe' replicates (all other stages
contribute zeros).

Differentiable end-to-end: reverse-mode AD through scan + ppermute
yields the standard backward pipeline (the transpose of a shifted
ppermute is the reverse shift).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def gpipe(stage_apply: Callable, stacked_params, x, *,
          mesh: Mesh, n_micro: int, axis_name: str = "pipe",
          data_axis: str = "data", seq_axis: str = None, key=None,
          with_aux: bool = False, extra=None, param_specs=None):
    """Run ``x`` through all pipeline stages.

    stage_apply(local_params, x_micro) applies one stage's layer stack
    to one microbatch; it is called inside shard_map, where every leaf
    of ``local_params`` is the device-local slice (leading dim
    total_layers/S) of ``stacked_params``.

    ``key`` (optional PRNG key) enables stochastic stages (dropout):
    stage_apply is then called as stage_apply(local_params, x_micro,
    key) with a key folded per (tick, stage) — unique randomness per
    microbatch per stage, identical math under AD.

    x: [B, T, C] (batch sharded over ``data_axis``); returns [B, T, C].
    ``seq_axis`` (SP x PP composition): when given, T is sharded over
    that mesh axis too and each stage body sees [mb, T/sp, C] — the
    stage must then handle the sequence sharding itself via axis-name
    collectives over ``seq_axis`` (Ulysses all-to-alls or ring
    ppermute rotations, tpunet/models/lm_pp.py). Executor logic is
    untouched: microbatching, ppermute hops and buffers all act on
    the batch dim only.

    ``with_aux`` (MoE x PP): stage_apply then returns ``(y, aux)``
    with ``aux`` a float32 scalar per (stage, microbatch) — e.g. the
    MoE load-balance term of the stage's layers — and the executor
    returns ``(out, aux_total)`` where ``aux_total`` is the SUM over
    stages and the MEAN over microbatches and data/seq shards
    (matching the equal-weight semantics gradient accumulation uses
    for count-independent loss terms, tpunet/train/steps.py). With
    pipe > 1 each microbatch-shard routes its tokens independently —
    per-shard stats, the standard shard_map MoE scope — whereas
    pipe == 1 routes the full global batch like the unpipelined model.

    ``extra`` (packed x PP): an optional per-example array [B, ...]
    (e.g. packed-sequence segment ids) microbatched alongside ``x``.
    It does NOT hop between stages: it is batch-constant metadata,
    replicated over 'pipe', so every stage just indexes its current
    microbatch's slice. Stage protocol becomes
    ``stage_apply(params, x_micro, extra_micro[, key])``.

    ``param_specs`` (EP x PP): an optional pytree of PartitionSpecs
    overriding the default ``P('pipe')`` per leaf — e.g. MoE expert
    stacks sharded ``P('pipe', 'model')`` so each device holds only
    its expert shard; the stage body then runs its own collectives
    over the extra axis (one psum per MoE layer in lm_pp).
    """
    n_stages = mesh.shape[axis_name]
    if n_stages == 1:
        args = ((x,) if extra is None else (x, extra))
        return (stage_apply(stacked_params, *args) if key is None
                else stage_apply(stacked_params, *args, key))

    _check_stacked(stacked_params, n_stages)

    p_specs = (param_specs if param_specs is not None else
               jax.tree_util.tree_map(lambda _: P(axis_name),
                                      stacked_params))
    x_spec = P(data_axis, seq_axis, None)
    out_specs = (x_spec, P()) if with_aux else x_spec
    has_extra = extra is not None
    e_spec = P(data_axis, seq_axis) if has_extra else None

    kw = dict(n_micro=n_micro, axis_name=axis_name, data_axis=data_axis,
              seq_axis=seq_axis, with_aux=with_aux, has_extra=has_extra)
    if key is None:
        body = functools.partial(_gpipe_body, stage_apply, **kw)
        in_specs = (p_specs, x_spec) + ((e_spec,) if has_extra else ())
        args = (stacked_params, x) + ((extra,) if has_extra else ())
    else:
        body = functools.partial(_gpipe_body_keyed, stage_apply, **kw)
        in_specs = ((p_specs, x_spec)
                    + ((e_spec,) if has_extra else ()) + (P(),))
        args = ((stacked_params, x)
                + ((extra,) if has_extra else ()) + (key,))

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False)
    return fn(*args)


def _gpipe_body_keyed(stage_apply, local_params, xl, *rest, n_micro,
                      axis_name, data_axis="data", seq_axis=None,
                      with_aux=False, has_extra=False):
    """_gpipe_body with a per-(tick, stage) folded PRNG key (always
    the LAST positional arg; an ``extra`` slice precedes it when
    present — see :func:`gpipe`'s stage protocol)."""
    key = rest[-1]
    s = jax.lax.axis_index(axis_name)

    def keyed_apply(params, x, *inner):
        # inner = (extra_micro?, step): fold the tick into the key and
        # forward everything but the step to the user's stage_apply.
        step = inner[-1]
        k = jax.random.fold_in(jax.random.fold_in(key, step), s)
        return stage_apply(params, x, *inner[:-1], k)

    return _gpipe_body(keyed_apply, local_params, xl, *rest[:-1],
                       n_micro=n_micro, axis_name=axis_name,
                       data_axis=data_axis, seq_axis=seq_axis,
                       with_aux=with_aux, has_extra=has_extra,
                       pass_step=True)


def _shard_norm(data_axis, seq_axis):
    """(grad/aux normalization axes, shard count over them)."""
    axes = (data_axis,) if seq_axis is None else (data_axis, seq_axis)
    n = 1
    for ax in axes:
        n = n * jax.lax.psum(1, ax)
    return axes, n


def _gpipe_body(stage_apply, local_params, xl, *rest, n_micro, axis_name,
                data_axis="data", seq_axis=None, with_aux=False,
                has_extra=False, pass_step=False):
    extra = rest[0] if has_extra else None
    s = jax.lax.axis_index(axis_name)
    n_stages = jax.lax.psum(1, axis_name)
    bl, t, c = xl.shape
    if bl % n_micro:
        raise ValueError(f"local batch {bl} not divisible by "
                         f"{n_micro} microbatches")
    mb = bl // n_micro
    xm = xl.reshape(n_micro, mb, t, c)
    em = (extra.reshape((n_micro, mb) + extra.shape[1:])
          if has_extra else None)
    perm = [(i, i + 1) for i in range(n_stages - 1)]  # no wraparound

    def tick(carry, step):
        act_in, outbuf, auxsum = carry
        m = step - s
        valid = (m >= 0) & (m < n_micro)
        mc = jnp.clip(m, 0, n_micro - 1)
        inp = jnp.where(s == 0,
                        jax.lax.dynamic_index_in_dim(xm, mc, 0,
                                                     keepdims=False),
                        act_in)
        args = (local_params, inp)
        if has_extra:
            args += (jax.lax.dynamic_index_in_dim(em, mc, 0,
                                                  keepdims=False),)
        y = (stage_apply(*args, step) if pass_step
             else stage_apply(*args))
        if with_aux:
            y, a = y
            auxsum = auxsum + jnp.where(valid,
                                        a.astype(jnp.float32), 0.0)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        is_last = s == n_stages - 1
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf,
            jnp.where(valid & is_last, y,
                      jax.lax.dynamic_index_in_dim(outbuf, mc, 0,
                                                   keepdims=False)),
            mc, 0)
        act_next = jax.lax.ppermute(y, axis_name, perm)
        return (act_next, outbuf, auxsum), None

    act0 = jnp.zeros((mb, t, c), xl.dtype)
    outbuf = jnp.zeros_like(xm)
    (_, outbuf, auxsum), _ = jax.lax.scan(
        tick, (act0, outbuf, jnp.zeros((), jnp.float32)),
        jnp.arange(n_micro + n_stages - 1))
    # Only the last stage wrote real activations; psum replicates them.
    outbuf = jax.lax.psum(
        jnp.where(s == n_stages - 1, outbuf, jnp.zeros_like(outbuf)),
        axis_name)
    out = outbuf.reshape(bl, t, c)
    if not with_aux:
        return out
    # Sum over stages ('pipe' psum), mean over microbatches and
    # data/seq shards (each routed its tokens independently).
    norm_axes, n_shards = _shard_norm(data_axis, seq_axis)
    aux = jax.lax.psum(jax.lax.psum(auxsum, axis_name), norm_axes)
    return out, aux / (n_micro * n_shards)


# ---------------------------------------------------------------------------
# 1F1B: manual-VJP executor with an interleaved fwd/bwd backward schedule.
# ---------------------------------------------------------------------------

def _check_stacked(stacked_params, n_stages: int) -> None:
    for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
        if leaf.shape[0] % n_stages:
            raise ValueError(
                f"stacked param {jax.tree_util.keystr(path)} has leading "
                f"(layer) dim {leaf.shape[0]} not divisible by "
                f"{n_stages} pipeline stages")


def onef1b_schedule(n_stages: int, n_micro: int) -> list:
    """The 1F1B tick table, host-side, for tests and inspection:
    ``table[t][s]`` is ``("F", m)``, ``("B", m)``, or ``None`` (idle).

    Closed form (the device-side scan uses the same integer math):
    forward of microbatch m runs at stage s on tick ``s + 2m``;
    backward on tick ``2S - 1 - s + 2m``. F-ticks at stage s all share
    parity ``s % 2`` and B-ticks parity ``(s+1) % 2``, so the two
    streams interleave without collision; the last stage runs
    ``F(m), B(m), F(m+1), B(m+1), ...`` — one-forward-one-backward.
    Total ticks ``2(M + S - 1)``, the same bubble fraction as GPipe
    (non-interleaved 1F1B improves memory, not bubble).
    """
    S, M = n_stages, n_micro
    total = 2 * (M + S - 1)
    table = [[None] * S for _ in range(total)]
    for s in range(S):
        for m in range(M):
            table[s + 2 * m][s] = ("F", m)
            table[2 * S - 1 - s + 2 * m][s] = ("B", m)
    return table


def onef1b(stage_apply: Callable, stacked_params, x, *,
           mesh: Mesh, n_micro: int, axis_name: str = "pipe",
           data_axis: str = "data", seq_axis: str = None, key=None,
           with_aux: bool = False, extra=None, param_specs=None,
           uniform_bwd: bool = None, ep_axis: str = None):
    """GPipe-compatible pipeline executor with a manual VJP whose
    backward runs the 1F1B schedule.

    Same contract as :func:`gpipe` (identical primal math, identical
    dropout key folding, so the two are grad-for-grad interchangeable —
    the parity tests assert it). The difference is memory: reverse-mode
    AD through the GPipe scan stacks EVERY per-tick intermediate (each
    stage's per-layer internals x ``M + S - 1`` ticks) as scan
    residuals, O(M) microbatches live at once. Here the forward is
    wrapped in ``jax.custom_vjp`` and saves only ``(params, x, key)``;
    the hand-written backward replays forwards and runs backwards in
    ONE combined scan in 1F1B order — forward of microbatch m at stage
    s on tick ``s + 2m``, backward on tick ``2S - 1 - s + 2m``
    (:func:`onef1b_schedule`) — holding a ring buffer of at most
    ``min(S, M)`` stage-input activations per device, the 1F1B
    in-flight bound. Per-tick vjp internals are transient (freed every
    tick), never stacked.

    Cost: one extra stage forward per microbatch (the replay), the
    standard price of rematerialized pipeline backward — the loss and
    its cotangent live OUTSIDE the executor (final LN/logits/CE run on
    the full output), so true no-remat 1F1B (loss inside the last
    stage) is not expressible at this interface. Collectives are
    hoisted out of the fwd/bwd branch (``lax.cond`` branches must not
    diverge on collectives): every tick runs exactly one forward-shift
    and one reverse-shift ``ppermute``, with zeros masked in for
    whichever stream a stage isn't driving. Under SP x PP
    (``seq_axis`` given) the stage BODY itself contains seq
    collectives, so the F/B ``lax.cond`` disappears entirely: each
    tick runs one ``jax.vjp`` on a role-selected input, keeping the
    collective sequence identical on every device every tick
    (branch-divergent in-stage collectives measurably corrupt
    gradients — see the body comment). Double differentiation is not
    supported (custom_vjp). ``with_aux`` matches :func:`gpipe`'s
    contract: stage_apply returns (y, aux); the executor returns
    (out, aux_total) and the manual backward pulls the aux cotangent
    through the same per-tick vjp as the activation cotangent.
    ``extra`` matches gpipe's contract too (per-microbatch metadata,
    e.g. packed segment ids) and is treated as NON-differentiable —
    its cotangent is zero. ``param_specs`` matches gpipe's (per-leaf
    spec override, e.g. expert stacks over ('pipe', 'model')).
    ``uniform_bwd`` forces the collective-uniform one-vjp-per-tick
    backward; it defaults to on exactly when ``seq_axis`` is given,
    and callers whose stage bodies contain OTHER in-stage collectives
    (EP's 'model' psums) must pass True themselves — in-stage
    collectives inside the diverging F/B lax.cond corrupt gradients
    (see the body comment). ``ep_axis`` (EP x PP): the mesh axis the
    stage bodies' expert psums run over; the manual backward then
    psums each tick's input-cotangent over it before shipping
    upstream — the per-tick vjp hands back only the LOCAL expert
    shard's cotangent paths (partial over the axis), and unlike
    gpipe-AD (whose shard_map transpose completes them via
    varying-manual-axes tracking) this hand-written boundary logic
    must restore replication itself, per tick, so the NEXT stage's
    expert-weight grads see a complete cotangent.
    """
    n_stages = mesh.shape[axis_name]
    has_extra = extra is not None
    # In-stage collectives categorically require the uniform backward;
    # resolve here so no caller can pass ep_axis without it.
    uniform_bwd = (bool(uniform_bwd) or seq_axis is not None
                   or ep_axis is not None)
    if n_stages == 1:
        args = ((x,) if extra is None else (x, extra))
        return (stage_apply(stacked_params, *args) if key is None
                else stage_apply(stacked_params, *args, key))
    _check_stacked(stacked_params, n_stages)

    p_specs = (param_specs if param_specs is not None else
               jax.tree_util.tree_map(lambda _: P(axis_name),
                                      stacked_params))
    x_spec = P(data_axis, seq_axis, None)
    keyed = key is not None
    kk = key if keyed else jnp.zeros((2,), jnp.uint32)
    # Fixed custom_vjp arity: a zero-size placeholder when no extra.
    ex = extra if has_extra else jnp.zeros((0,), jnp.int32)
    e_spec = P(data_axis, seq_axis) if has_extra else P()

    fwd_out_specs = (x_spec, P()) if with_aux else x_spec
    kw = dict(n_micro=n_micro, axis_name=axis_name, data_axis=data_axis,
              seq_axis=seq_axis, with_aux=with_aux, has_extra=has_extra)

    def fwd_program(params, xx, exx, k):
        e_args = (exx,) if has_extra else ()
        e_in = (e_spec,) if has_extra else ()
        if keyed:
            body = functools.partial(_gpipe_body_keyed, stage_apply,
                                     **kw)
            return jax.shard_map(
                body, mesh=mesh,
                in_specs=(p_specs, x_spec) + e_in + (P(),),
                out_specs=fwd_out_specs, check_vma=False)(
                    params, xx, *e_args, k)
        body = functools.partial(_gpipe_body, stage_apply, **kw)
        return jax.shard_map(
            body, mesh=mesh, in_specs=(p_specs, x_spec) + e_in,
            out_specs=fwd_out_specs, check_vma=False)(
                params, xx, *e_args)

    def bwd_program(params, xx, exx, k, dy, daux):
        body = functools.partial(_onef1b_bwd_body, stage_apply,
                                 n_stages=n_stages, keyed=keyed,
                                 uniform_bwd=uniform_bwd,
                                 ep_axis=ep_axis,
                                 param_specs=p_specs, **kw)
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, x_spec, e_spec, P(), x_spec, P()),
            out_specs=(p_specs, x_spec), check_vma=False)(
                params, xx, exx, k, dy, daux)

    @jax.custom_vjp
    def run(params, xx, exx, k):
        return fwd_program(params, xx, exx, k)

    def run_fwd(params, xx, exx, k):
        return fwd_program(params, xx, exx, k), (params, xx, exx, k)

    def run_bwd(res, ct):
        params, xx, exx, k = res
        if with_aux:
            dy, daux = ct
        else:
            dy, daux = ct, jnp.zeros((), jnp.float32)
        dparams, dx = bwd_program(params, xx, exx, k, dy,
                                  daux.astype(jnp.float32))
        # PRNG keys and (integer) extras have float0 cotangents.
        dk = np.zeros(np.shape(k), dtype=jax.dtypes.float0)
        dex = (np.zeros(np.shape(exx), dtype=jax.dtypes.float0)
               if jnp.issubdtype(exx.dtype, jnp.integer)
               else jnp.zeros_like(exx))
        return dparams, dx, dex, dk

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, x, ex, kk)


def _onef1b_bwd_body(stage_apply, local_params, xl, exl, key, dyl,
                     dauxl=None, *, n_micro, axis_name, data_axis,
                     seq_axis, n_stages, keyed, with_aux=False,
                     has_extra=False, uniform_bwd=False, ep_axis=None,
                     param_specs=None):
    """Device-local 1F1B backward: one scan over 2(M+S-1) ticks.

    Carry: (act_in, cot_in, resid ring, dparam accumulator fp32,
    dx buffer). Each tick a stage is an F-tick (replay one stage
    forward, save its input to the ring, ship the activation down),
    a B-tick (vjp the saved input against the incoming cotangent,
    accumulate dparams, ship the input-cotangent up), or idle
    (masked). F/B tick parities differ per stage (onef1b_schedule), so
    one ``lax.cond`` picks the work; both ppermutes run unconditionally
    with masked zeros. With ``with_aux`` each B-tick's vjp also pulls
    the executor-level aux cotangent ``daux / (M * n_shards)`` — the
    transpose of the forward's sum-over-stages / mean-over-
    microbatch-shards aux reduction (:func:`_gpipe_body`).
    """
    s = jax.lax.axis_index(axis_name)
    S, M = n_stages, n_micro
    bl, t, c = xl.shape
    if bl % M:
        raise ValueError(f"local batch {bl} not divisible by "
                         f"{M} microbatches")
    mb = bl // M
    xm = xl.reshape(M, mb, t, c)
    dym = dyl.reshape(M, mb, t, c)
    exm = (exl.reshape((M, mb) + exl.shape[1:]) if has_extra else None)
    epn = jax.lax.psum(1, ep_axis) if ep_axis is not None else 1
    if ep_axis is not None:
        # In-stage EP psums put this backward in JAX's UNREDUCED
        # cotangent convention (psum's transpose inside jax.vjp is
        # psum — it COMPLETES a per-device partial cotangent; feeding
        # it a complete/replicated one doubles everything downstream).
        # Speak the convention: divide the entering cotangent by the
        # axis size so every cotangent in the scan is an unreduced
        # 1/ep share, then complete each result at the end — psum over
        # ep for every leaf NOT sharded over it, and for dx (both
        # replicated over ep); model-sharded leaves complete without
        # the ep psum. Permutation collectives (SP's ppermute /
        # all_to_all) are convention-agnostic, so SP x EP composes.
        dym = dym / epn
    if with_aux:
        _, n_shards = _shard_norm(data_axis, seq_axis)
        aux_ct = dauxl.astype(jnp.float32) / (M * n_shards * epn)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    rev_perm = [(i + 1, i) for i in range(S - 1)]
    n_buf = min(S, M)   # 1F1B in-flight bound (residency at stage s
    #                     is S - s microbatches; see overwrite proof
    #                     in tests/test_pp_1f1b.py)

    def apply_f(params, inp, m):
        args = (params, inp)
        if has_extra:
            args += (jax.lax.dynamic_index_in_dim(exm, m, 0,
                                                  keepdims=False),)
        if keyed:
            # EXACTLY _gpipe_body_keyed's folding — fwd tick = m + s —
            # so replayed dropout masks match the primal bit-for-bit.
            k = jax.random.fold_in(jax.random.fold_in(key, m + s), s)
            return stage_apply(*args, k)
        return stage_apply(*args)

    def tick(carry, t_):
        act_in, cot_in, resid, dpsum, dxbuf = carry
        df = t_ - s
        m_f = df // 2
        f_valid = (df >= 0) & (df % 2 == 0) & (m_f < M)
        db = t_ - (2 * S - 1 - s)
        m_b = db // 2
        b_valid = (db >= 0) & (db % 2 == 0) & (m_b < M)
        m_fc = jnp.clip(m_f, 0, M - 1)
        m_bc = jnp.clip(m_b, 0, M - 1)

        f_inp = jnp.where(
            s == 0,
            jax.lax.dynamic_index_in_dim(xm, m_fc, 0, keepdims=False),
            act_in)
        g_in = jnp.where(
            s == S - 1,
            jax.lax.dynamic_index_in_dim(dym, m_bc, 0, keepdims=False),
            cot_in)
        b_slot = m_bc % n_buf
        b_inp = jax.lax.dynamic_index_in_dim(resid, b_slot, 0,
                                             keepdims=False)

        if uniform_bwd:
            # SP x PP / EP x PP: the stage body contains collectives
            # (seq-axis ring ppermutes / Ulysses all-to-alls, or EP's
            # 'model' psums).
            # Those must NOT sit inside diverging lax.cond branches:
            # the F/B predicate varies over 'pipe', so stages would
            # execute DIFFERENT collective ops whose participant sets
            # span all stages — undefined pairing (measured: wrong
            # gradients with a ring stage; a deadlock risk on real
            # ICI). Instead run ONE vjp per tick on a role-selected
            # input — every device then executes an identical
            # collective sequence every tick; the unused half of each
            # (primal, pulled-grad) pair is masked below. Costs a
            # wasted pull on F-ticks, the price of collective
            # uniformity.
            m_sel = jnp.where(f_valid, m_fc, m_bc)
            inp = jnp.where(f_valid, f_inp, b_inp)
            y, pull = jax.vjp(lambda p, xi: apply_f(p, xi, m_sel),
                              local_params, inp)
            if with_aux:
                y, _ = y
                dp, dx = pull((g_in, aux_ct))
            else:
                dp, dx = pull(g_in)
        else:
            # No seq sharding -> stage bodies are collective-free and
            # the cheap schedule runs only the branch each tick needs.
            zero_dp = jax.tree_util.tree_map(jnp.zeros_like,
                                             local_params)

            def do_f(_):
                yf = apply_f(local_params, f_inp, m_fc)
                if with_aux:
                    yf = yf[0]
                return yf, jnp.zeros_like(f_inp), zero_dp

            def do_b(_):
                # Recompute this stage's forward and pull the cotangent
                # back through it — idle ticks also land here on zeros,
                # masked out below (dp/dx are b_valid-masked, so the
                # unmasked aux cotangent never leaks from idle ticks).
                _, pull = jax.vjp(lambda p, xi: apply_f(p, xi, m_bc),
                                  local_params, b_inp)
                dpb, dxb = pull((g_in, aux_ct) if with_aux else g_in)
                return jnp.zeros_like(f_inp), dxb, dpb

            y, dx, dp = jax.lax.cond(f_valid, do_f, do_b, None)
        y = jnp.where(f_valid, y, jnp.zeros_like(y))
        dx = jnp.where(b_valid, dx, jnp.zeros_like(dx))
        dpsum = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(b_valid, g,
                                           jnp.zeros_like(g)
                                           ).astype(acc.dtype),
            dpsum, dp)

        f_slot = m_fc % n_buf
        old = jax.lax.dynamic_index_in_dim(resid, f_slot, 0,
                                           keepdims=False)
        resid = jax.lax.dynamic_update_index_in_dim(
            resid, jnp.where(f_valid, f_inp, old), f_slot, 0)
        oldx = jax.lax.dynamic_index_in_dim(dxbuf, m_bc, 0,
                                            keepdims=False)
        dxbuf = jax.lax.dynamic_update_index_in_dim(
            dxbuf, jnp.where(b_valid & (s == 0), dx, oldx), m_bc, 0)

        act_next = jax.lax.ppermute(y, axis_name, fwd_perm)
        cot_next = jax.lax.ppermute(dx, axis_name, rev_perm)
        return (act_next, cot_next, resid, dpsum, dxbuf), None

    carry0 = (
        jnp.zeros((mb, t, c), xl.dtype),
        jnp.zeros((mb, t, c), dyl.dtype),
        jnp.zeros((n_buf, mb, t, c), xl.dtype),
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), local_params),
        jnp.zeros_like(dym),
    )
    (_, _, _, dpsum, dxbuf), _ = jax.lax.scan(
        tick, carry0, jnp.arange(2 * (M + S - 1)))
    # Stage 0 holds the real input-cotangents; replicate like the
    # forward's output buffer. dparams stay per-stage (out spec 'pipe')
    # but each data shard only saw ITS microbatches — and under SP x PP
    # each seq shard only its token slice — so sum the partial param
    # grads over 'data' AND (when sharded) the seq axis: exactly the
    # psums GPipe-AD's transpose inserts for every mesh axis the
    # params' in_spec replicates over but the cotangent varies over.
    # (dx needs no seq psum: its out_spec CARRIES the seq sharding.)
    # Under EP the unreduced-convention shares (see the dym / epn note)
    # complete here too: psum over ep for dx and for every leaf NOT
    # sharded over the ep axis; ep-sharded leaves hold per-shard grads
    # and must not mix.
    dx_axes = ((axis_name,) if ep_axis is None
               else (axis_name, ep_axis))
    dx = jax.lax.psum(
        jnp.where(s == 0, dxbuf, jnp.zeros_like(dxbuf)), dx_axes)
    grad_axes = ((data_axis,) if seq_axis is None
                 else (data_axis, seq_axis))

    def leaf_axes(spec):
        if ep_axis is None or (spec is not None
                               and ep_axis in tuple(spec)):
            return grad_axes
        return grad_axes + (ep_axis,)

    # PartitionSpec is a tuple subclass (a pytree NODE), so flatten the
    # spec tree with is_leaf instead of a joint tree_map.
    flat_p, treedef = jax.tree_util.tree_flatten(local_params)
    flat_acc = jax.tree_util.tree_leaves(dpsum)
    if param_specs is None:
        flat_specs = [None] * len(flat_p)
    else:
        flat_specs = jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda v: isinstance(v, P))
    dparams = treedef.unflatten([
        jax.lax.psum(acc, leaf_axes(sp_)).astype(p.dtype)
        for acc, p, sp_ in zip(flat_acc, flat_p, flat_specs)])
    return dparams, dx.reshape(bl, t, c)
