"""Pipeline parallelism: GPipe-style SPMD executor over the 'pipe' axis.

The reference has no pipeline parallelism (single-file model, SURVEY.md
2b); tpunet implements it the TPU way: no per-stage processes, no
send/recv threads — ONE jitted SPMD program in which every device runs
the same code, holds one pipeline stage's worth of stacked layer
parameters (leading dim sharded over 'pipe'), and activations hop
stage-to-stage with ``lax.ppermute`` (one ICI neighbor hop per tick).

Schedule: plain GPipe with M microbatches over S stages; the static
scan runs M + S - 1 ticks. At tick t, stage s computes microbatch
m = t - s (masked out when m is out of range — idle bubble ticks
compute on zeros and are discarded). Stage 0 reads microbatches from
the (replicated) input; stage S-1 accumulates results into the output
buffer, which a final psum over 'pipe' replicates (all other stages
contribute zeros).

Differentiable end-to-end: reverse-mode AD through scan + ppermute
yields the standard backward pipeline (the transpose of a shifted
ppermute is the reverse shift).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.compat import shard_map


def gpipe(stage_apply: Callable, stacked_params, x, *,
          mesh: Mesh, n_micro: int, axis_name: str = "pipe",
          data_axis: str = "data", seq_axis: str = None, key=None,
          with_aux: bool = False, extra=None, param_specs=None):
    """Run ``x`` through all pipeline stages.

    stage_apply(local_params, x_micro) applies one stage's layer stack
    to one microbatch; it is called inside shard_map, where every leaf
    of ``local_params`` is the device-local slice (leading dim
    total_layers/S) of ``stacked_params``.

    ``key`` (optional PRNG key) enables stochastic stages (dropout):
    stage_apply is then called as stage_apply(local_params, x_micro,
    key) with a key folded per (tick, stage) — unique randomness per
    microbatch per stage, identical math under AD.

    x: [B, T, C] (batch sharded over ``data_axis``); returns [B, T, C].
    ``seq_axis`` (SP x PP composition): when given, T is sharded over
    that mesh axis too and each stage body sees [mb, T/sp, C] — the
    stage must then handle the sequence sharding itself via axis-name
    collectives over ``seq_axis`` (Ulysses all-to-alls or ring
    ppermute rotations, tpunet/models/lm_pp.py). Executor logic is
    untouched: microbatching, ppermute hops and buffers all act on
    the batch dim only.

    ``with_aux`` (MoE x PP): stage_apply then returns ``(y, aux)``
    with ``aux`` a float32 scalar per (stage, microbatch) — e.g. the
    MoE load-balance term of the stage's layers — and the executor
    returns ``(out, aux_total)`` where ``aux_total`` is the SUM over
    stages and the MEAN over microbatches and data/seq shards
    (matching the equal-weight semantics gradient accumulation uses
    for count-independent loss terms, tpunet/train/steps.py). With
    pipe > 1 each microbatch-shard routes its tokens independently —
    per-shard stats, the standard shard_map MoE scope — whereas
    pipe == 1 routes the full global batch like the unpipelined model.

    ``extra`` (packed x PP): an optional per-example array [B, ...]
    (e.g. packed-sequence segment ids) microbatched alongside ``x``.
    It does NOT hop between stages: it is batch-constant metadata,
    replicated over 'pipe', so every stage just indexes its current
    microbatch's slice. Stage protocol becomes
    ``stage_apply(params, x_micro, extra_micro[, key])``.

    ``param_specs`` (EP x PP): an optional pytree of PartitionSpecs
    overriding the default ``P('pipe')`` per leaf — e.g. MoE expert
    stacks sharded ``P('pipe', 'model')`` so each device holds only
    its expert shard; the stage body then runs its own collectives
    over the extra axis (one psum per MoE layer in lm_pp).
    """
    n_stages = mesh.shape[axis_name]
    if n_stages == 1:
        args = ((x,) if extra is None else (x, extra))
        return (stage_apply(stacked_params, *args) if key is None
                else stage_apply(stacked_params, *args, key))

    _check_stacked(stacked_params, n_stages)

    p_specs = (param_specs if param_specs is not None else
               jax.tree_util.tree_map(lambda _: P(axis_name),
                                      stacked_params))
    x_spec = P(data_axis, seq_axis, None)
    out_specs = (x_spec, P()) if with_aux else x_spec
    has_extra = extra is not None
    e_spec = P(data_axis, seq_axis) if has_extra else None

    kw = dict(n_micro=n_micro, axis_name=axis_name, data_axis=data_axis,
              seq_axis=seq_axis, with_aux=with_aux, has_extra=has_extra)
    if key is None:
        body = functools.partial(_gpipe_body, stage_apply, **kw)
        in_specs = (p_specs, x_spec) + ((e_spec,) if has_extra else ())
        args = (stacked_params, x) + ((extra,) if has_extra else ())
    else:
        body = functools.partial(_gpipe_body_keyed, stage_apply, **kw)
        in_specs = ((p_specs, x_spec)
                    + ((e_spec,) if has_extra else ()) + (P(),))
        args = ((stacked_params, x)
                + ((extra,) if has_extra else ()) + (key,))

    fn = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False)
    return fn(*args)


def _gpipe_body_keyed(stage_apply, local_params, xl, *rest, n_micro,
                      axis_name, data_axis="data", seq_axis=None,
                      with_aux=False, has_extra=False):
    """_gpipe_body with a per-(tick, stage) folded PRNG key (always
    the LAST positional arg; an ``extra`` slice precedes it when
    present — see :func:`gpipe`'s stage protocol)."""
    key = rest[-1]
    s = jax.lax.axis_index(axis_name)

    def keyed_apply(params, x, *inner):
        # inner = (extra_micro?, step): fold the tick into the key and
        # forward everything but the step to the user's stage_apply.
        step = inner[-1]
        k = jax.random.fold_in(jax.random.fold_in(key, step), s)
        return stage_apply(params, x, *inner[:-1], k)

    return _gpipe_body(keyed_apply, local_params, xl, *rest[:-1],
                       n_micro=n_micro, axis_name=axis_name,
                       data_axis=data_axis, seq_axis=seq_axis,
                       with_aux=with_aux, has_extra=has_extra,
                       pass_step=True)


def _shard_norm(data_axis, seq_axis):
    """(grad/aux normalization axes, shard count over them)."""
    axes = (data_axis,) if seq_axis is None else (data_axis, seq_axis)
    n = 1
    for ax in axes:
        n = n * jax.lax.psum(1, ax)
    return axes, n


def _gpipe_body(stage_apply, local_params, xl, *rest, n_micro, axis_name,
                data_axis="data", seq_axis=None, with_aux=False,
                has_extra=False, pass_step=False):
    extra = rest[0] if has_extra else None
    s = jax.lax.axis_index(axis_name)
    n_stages = jax.lax.psum(1, axis_name)
    bl, t, c = xl.shape
    if bl % n_micro:
        raise ValueError(f"local batch {bl} not divisible by "
                         f"{n_micro} microbatches")
    mb = bl // n_micro
    xm = xl.reshape(n_micro, mb, t, c)
    em = (extra.reshape((n_micro, mb) + extra.shape[1:])
          if has_extra else None)
    perm = [(i, i + 1) for i in range(n_stages - 1)]  # no wraparound

    def tick(carry, step):
        act_in, outbuf, auxsum = carry
        m = step - s
        valid = (m >= 0) & (m < n_micro)
        mc = jnp.clip(m, 0, n_micro - 1)
        inp = jnp.where(s == 0,
                        jax.lax.dynamic_index_in_dim(xm, mc, 0,
                                                     keepdims=False),
                        act_in)
        args = (local_params, inp)
        if has_extra:
            args += (jax.lax.dynamic_index_in_dim(em, mc, 0,
                                                  keepdims=False),)
        y = (stage_apply(*args, step) if pass_step
             else stage_apply(*args))
        if with_aux:
            y, a = y
            auxsum = auxsum + jnp.where(valid,
                                        a.astype(jnp.float32), 0.0)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        is_last = s == n_stages - 1
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf,
            jnp.where(valid & is_last, y,
                      jax.lax.dynamic_index_in_dim(outbuf, mc, 0,
                                                   keepdims=False)),
            mc, 0)
        act_next = jax.lax.ppermute(y, axis_name, perm)
        return (act_next, outbuf, auxsum), None

    act0 = jnp.zeros((mb, t, c), xl.dtype)
    outbuf = jnp.zeros_like(xm)
    (_, outbuf, auxsum), _ = jax.lax.scan(
        tick, (act0, outbuf, jnp.zeros((), jnp.float32)),
        jnp.arange(n_micro + n_stages - 1))
    # Only the last stage wrote real activations; psum replicates them.
    outbuf = jax.lax.psum(
        jnp.where(s == n_stages - 1, outbuf, jnp.zeros_like(outbuf)),
        axis_name)
    out = outbuf.reshape(bl, t, c)
    if not with_aux:
        return out
    # Sum over stages ('pipe' psum), mean over microbatches and
    # data/seq shards (each routed its tokens independently).
    norm_axes, n_shards = _shard_norm(data_axis, seq_axis)
    aux = jax.lax.psum(jax.lax.psum(auxsum, axis_name), norm_axes)
    return out, aux / (n_micro * n_shards)


# ---------------------------------------------------------------------------
# 1F1B: manual-VJP executor with an interleaved fwd/bwd backward schedule.
# ---------------------------------------------------------------------------

def _check_stacked(stacked_params, n_stages: int) -> None:
    for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
        if leaf.shape[0] % n_stages:
            raise ValueError(
                f"stacked param {jax.tree_util.keystr(path)} has leading "
                f"(layer) dim {leaf.shape[0]} not divisible by "
                f"{n_stages} pipeline stages")


def onef1b_schedule(n_stages: int, n_micro: int) -> list:
    """The 1F1B tick table, host-side, for tests and inspection:
    ``table[t][s]`` is ``("F", m)``, ``("B", m)``, or ``None`` (idle).

    Closed form (the device-side scan uses the same integer math):
    forward of microbatch m runs at stage s on tick ``s + 2m``;
    backward on tick ``2S - 1 - s + 2m``. F-ticks at stage s all share
    parity ``s % 2`` and B-ticks parity ``(s+1) % 2``, so the two
    streams interleave without collision; the last stage runs
    ``F(m), B(m), F(m+1), B(m+1), ...`` — one-forward-one-backward.
    Total ticks ``2(M + S - 1)``, the same bubble fraction as GPipe
    (non-interleaved 1F1B improves memory, not bubble).
    """
    S, M = n_stages, n_micro
    total = 2 * (M + S - 1)
    table = [[None] * S for _ in range(total)]
    for s in range(S):
        for m in range(M):
            table[s + 2 * m][s] = ("F", m)
            table[2 * S - 1 - s + 2 * m][s] = ("B", m)
    return table


def onef1b(stage_apply: Callable, stacked_params, x, *,
           mesh: Mesh, n_micro: int, axis_name: str = "pipe",
           data_axis: str = "data", seq_axis: str = None, key=None,
           with_aux: bool = False, extra=None, param_specs=None,
           uniform_bwd: bool = None, ep_axis: str = None):
    """GPipe-compatible pipeline executor with a manual VJP whose
    backward runs the 1F1B schedule.

    Same contract as :func:`gpipe` (identical primal math, identical
    dropout key folding, so the two are grad-for-grad interchangeable —
    the parity tests assert it). The difference is memory: reverse-mode
    AD through the GPipe scan stacks EVERY per-tick intermediate (each
    stage's per-layer internals x ``M + S - 1`` ticks) as scan
    residuals, O(M) microbatches live at once. Here the forward is
    wrapped in ``jax.custom_vjp`` and saves only ``(params, x, key)``;
    the hand-written backward replays forwards and runs backwards in
    ONE combined scan in 1F1B order — forward of microbatch m at stage
    s on tick ``s + 2m``, backward on tick ``2S - 1 - s + 2m``
    (:func:`onef1b_schedule`) — holding a ring buffer of at most
    ``min(S, M)`` stage-input activations per device, the 1F1B
    in-flight bound. Per-tick vjp internals are transient (freed every
    tick), never stacked.

    Cost: one extra stage forward per microbatch (the replay), the
    standard price of rematerialized pipeline backward — the loss and
    its cotangent live OUTSIDE the executor (final LN/logits/CE run on
    the full output), so true no-remat 1F1B (loss inside the last
    stage) is not expressible at this interface. Collectives are
    hoisted out of the fwd/bwd branch (``lax.cond`` branches must not
    diverge on collectives): every tick runs exactly one forward-shift
    and one reverse-shift ``ppermute``, with zeros masked in for
    whichever stream a stage isn't driving. Under SP x PP
    (``seq_axis`` given) the stage BODY itself contains seq
    collectives, so the F/B ``lax.cond`` disappears entirely: each
    tick runs one ``jax.vjp`` on a role-selected input, keeping the
    collective sequence identical on every device every tick
    (branch-divergent in-stage collectives measurably corrupt
    gradients — see the body comment). Double differentiation is not
    supported (custom_vjp). ``with_aux`` matches :func:`gpipe`'s
    contract: stage_apply returns (y, aux); the executor returns
    (out, aux_total) and the manual backward pulls the aux cotangent
    through the same per-tick vjp as the activation cotangent.
    ``extra`` matches gpipe's contract too (per-microbatch metadata,
    e.g. packed segment ids) and is treated as NON-differentiable —
    its cotangent is zero. ``param_specs`` matches gpipe's (per-leaf
    spec override, e.g. expert stacks over ('pipe', 'model')).
    ``uniform_bwd`` forces the collective-uniform one-vjp-per-tick
    backward; it defaults to on exactly when ``seq_axis`` is given,
    and callers whose stage bodies contain OTHER in-stage collectives
    (EP's 'model' psums) must pass True themselves — in-stage
    collectives inside the diverging F/B lax.cond corrupt gradients
    (see the body comment). ``ep_axis`` (EP x PP): the mesh axis the
    stage bodies' expert psums run over; the manual backward then
    psums each tick's input-cotangent over it before shipping
    upstream — the per-tick vjp hands back only the LOCAL expert
    shard's cotangent paths (partial over the axis), and unlike
    gpipe-AD (whose shard_map transpose completes them via
    varying-manual-axes tracking) this hand-written boundary logic
    must restore replication itself, per tick, so the NEXT stage's
    expert-weight grads see a complete cotangent.
    """
    n_stages = mesh.shape[axis_name]
    has_extra = extra is not None
    # In-stage collectives categorically require the uniform backward;
    # resolve here so no caller can pass ep_axis without it.
    uniform_bwd = (bool(uniform_bwd) or seq_axis is not None
                   or ep_axis is not None)
    if n_stages == 1:
        args = ((x,) if extra is None else (x, extra))
        return (stage_apply(stacked_params, *args) if key is None
                else stage_apply(stacked_params, *args, key))
    _check_stacked(stacked_params, n_stages)

    p_specs = (param_specs if param_specs is not None else
               jax.tree_util.tree_map(lambda _: P(axis_name),
                                      stacked_params))
    x_spec = P(data_axis, seq_axis, None)
    keyed = key is not None
    kk = key if keyed else jnp.zeros((2,), jnp.uint32)
    # Fixed custom_vjp arity: a zero-size placeholder when no extra.
    ex = extra if has_extra else jnp.zeros((0,), jnp.int32)
    e_spec = P(data_axis, seq_axis) if has_extra else P()

    fwd_out_specs = (x_spec, P()) if with_aux else x_spec
    kw = dict(n_micro=n_micro, axis_name=axis_name, data_axis=data_axis,
              seq_axis=seq_axis, with_aux=with_aux, has_extra=has_extra)

    def fwd_program(params, xx, exx, k):
        e_args = (exx,) if has_extra else ()
        e_in = (e_spec,) if has_extra else ()
        if keyed:
            body = functools.partial(_gpipe_body_keyed, stage_apply,
                                     **kw)
            return shard_map(
                body, mesh=mesh,
                in_specs=(p_specs, x_spec) + e_in + (P(),),
                out_specs=fwd_out_specs, check_vma=False)(
                    params, xx, *e_args, k)
        body = functools.partial(_gpipe_body, stage_apply, **kw)
        return shard_map(
            body, mesh=mesh, in_specs=(p_specs, x_spec) + e_in,
            out_specs=fwd_out_specs, check_vma=False)(
                params, xx, *e_args)

    def bwd_program(params, xx, exx, k, dy, daux):
        body = functools.partial(_onef1b_bwd_body, stage_apply,
                                 n_stages=n_stages, keyed=keyed,
                                 uniform_bwd=uniform_bwd,
                                 ep_axis=ep_axis,
                                 param_specs=p_specs, **kw)
        return shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, x_spec, e_spec, P(), x_spec, P()),
            out_specs=(p_specs, x_spec), check_vma=False)(
                params, xx, exx, k, dy, daux)

    @jax.custom_vjp
    def run(params, xx, exx, k):
        return fwd_program(params, xx, exx, k)

    def run_fwd(params, xx, exx, k):
        return fwd_program(params, xx, exx, k), (params, xx, exx, k)

    def run_bwd(res, ct):
        params, xx, exx, k = res
        if with_aux:
            dy, daux = ct
        else:
            dy, daux = ct, jnp.zeros((), jnp.float32)
        dparams, dx = bwd_program(params, xx, exx, k, dy,
                                  daux.astype(jnp.float32))
        # PRNG keys and (integer) extras have float0 cotangents.
        dk = np.zeros(np.shape(k), dtype=jax.dtypes.float0)
        dex = (np.zeros(np.shape(exx), dtype=jax.dtypes.float0)
               if jnp.issubdtype(exx.dtype, jnp.integer)
               else jnp.zeros_like(exx))
        return dparams, dx, dex, dk

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, x, ex, kk)


def _onef1b_bwd_body(stage_apply, local_params, xl, exl, key, dyl,
                     dauxl=None, *, n_micro, axis_name, data_axis,
                     seq_axis, n_stages, keyed, with_aux=False,
                     has_extra=False, uniform_bwd=False, ep_axis=None,
                     param_specs=None):
    """Device-local 1F1B backward: one scan over 2(M+S-1) ticks.

    Carry: (act_in, cot_in, resid ring, dparam accumulator fp32,
    dx buffer). Each tick a stage is an F-tick (replay one stage
    forward, save its input to the ring, ship the activation down),
    a B-tick (vjp the saved input against the incoming cotangent,
    accumulate dparams, ship the input-cotangent up), or idle
    (masked). F/B tick parities differ per stage (onef1b_schedule), so
    one ``lax.cond`` picks the work; both ppermutes run unconditionally
    with masked zeros. With ``with_aux`` each B-tick's vjp also pulls
    the executor-level aux cotangent ``daux / (M * n_shards)`` — the
    transpose of the forward's sum-over-stages / mean-over-
    microbatch-shards aux reduction (:func:`_gpipe_body`).
    """
    s = jax.lax.axis_index(axis_name)
    S, M = n_stages, n_micro
    bl, t, c = xl.shape
    if bl % M:
        raise ValueError(f"local batch {bl} not divisible by "
                         f"{M} microbatches")
    mb = bl // M
    xm = xl.reshape(M, mb, t, c)
    dym = dyl.reshape(M, mb, t, c)
    exm = (exl.reshape((M, mb) + exl.shape[1:]) if has_extra else None)
    epn = jax.lax.psum(1, ep_axis) if ep_axis is not None else 1
    if ep_axis is not None:
        # In-stage EP psums put this backward in JAX's UNREDUCED
        # cotangent convention (psum's transpose inside jax.vjp is
        # psum — it COMPLETES a per-device partial cotangent; feeding
        # it a complete/replicated one doubles everything downstream).
        # Speak the convention: divide the entering cotangent by the
        # axis size so every cotangent in the scan is an unreduced
        # 1/ep share, then complete each result at the end — psum over
        # ep for every leaf NOT sharded over it, and for dx (both
        # replicated over ep); model-sharded leaves complete without
        # the ep psum. Permutation collectives (SP's ppermute /
        # all_to_all) are convention-agnostic, so SP x EP composes.
        dym = dym / epn
    if with_aux:
        _, n_shards = _shard_norm(data_axis, seq_axis)
        aux_ct = dauxl.astype(jnp.float32) / (M * n_shards * epn)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    rev_perm = [(i + 1, i) for i in range(S - 1)]
    n_buf = min(S, M)   # 1F1B in-flight bound (residency at stage s
    #                     is S - s microbatches; see overwrite proof
    #                     in tests/test_pp_1f1b.py)

    def apply_f(params, inp, m):
        args = (params, inp)
        if has_extra:
            args += (jax.lax.dynamic_index_in_dim(exm, m, 0,
                                                  keepdims=False),)
        if keyed:
            # EXACTLY _gpipe_body_keyed's folding — fwd tick = m + s —
            # so replayed dropout masks match the primal bit-for-bit.
            k = jax.random.fold_in(jax.random.fold_in(key, m + s), s)
            return stage_apply(*args, k)
        return stage_apply(*args)

    def tick(carry, t_):
        act_in, cot_in, resid, dpsum, dxbuf = carry
        df = t_ - s
        m_f = df // 2
        f_valid = (df >= 0) & (df % 2 == 0) & (m_f < M)
        db = t_ - (2 * S - 1 - s)
        m_b = db // 2
        b_valid = (db >= 0) & (db % 2 == 0) & (m_b < M)
        m_fc = jnp.clip(m_f, 0, M - 1)
        m_bc = jnp.clip(m_b, 0, M - 1)

        f_inp = jnp.where(
            s == 0,
            jax.lax.dynamic_index_in_dim(xm, m_fc, 0, keepdims=False),
            act_in)
        g_in = jnp.where(
            s == S - 1,
            jax.lax.dynamic_index_in_dim(dym, m_bc, 0, keepdims=False),
            cot_in)
        b_slot = m_bc % n_buf
        b_inp = jax.lax.dynamic_index_in_dim(resid, b_slot, 0,
                                             keepdims=False)

        if uniform_bwd:
            # SP x PP / EP x PP: the stage body contains collectives
            # (seq-axis ring ppermutes / Ulysses all-to-alls, or EP's
            # 'model' psums).
            # Those must NOT sit inside diverging lax.cond branches:
            # the F/B predicate varies over 'pipe', so stages would
            # execute DIFFERENT collective ops whose participant sets
            # span all stages — undefined pairing (measured: wrong
            # gradients with a ring stage; a deadlock risk on real
            # ICI). Instead run ONE vjp per tick on a role-selected
            # input — every device then executes an identical
            # collective sequence every tick; the unused half of each
            # (primal, pulled-grad) pair is masked below. Costs a
            # wasted pull on F-ticks, the price of collective
            # uniformity.
            m_sel = jnp.where(f_valid, m_fc, m_bc)
            inp = jnp.where(f_valid, f_inp, b_inp)
            y, pull = jax.vjp(lambda p, xi: apply_f(p, xi, m_sel),
                              local_params, inp)
            if with_aux:
                y, _ = y
                dp, dx = pull((g_in, aux_ct))
            else:
                dp, dx = pull(g_in)
        else:
            # No seq sharding -> stage bodies are collective-free and
            # the cheap schedule runs only the branch each tick needs.
            zero_dp = jax.tree_util.tree_map(jnp.zeros_like,
                                             local_params)

            def do_f(_):
                yf = apply_f(local_params, f_inp, m_fc)
                if with_aux:
                    yf = yf[0]
                return yf, jnp.zeros_like(f_inp), zero_dp

            def do_b(_):
                # Recompute this stage's forward and pull the cotangent
                # back through it — idle ticks also land here on zeros,
                # masked out below (dp/dx are b_valid-masked, so the
                # unmasked aux cotangent never leaks from idle ticks).
                _, pull = jax.vjp(lambda p, xi: apply_f(p, xi, m_bc),
                                  local_params, b_inp)
                dpb, dxb = pull((g_in, aux_ct) if with_aux else g_in)
                return jnp.zeros_like(f_inp), dxb, dpb

            y, dx, dp = jax.lax.cond(f_valid, do_f, do_b, None)
        y = jnp.where(f_valid, y, jnp.zeros_like(y))
        dx = jnp.where(b_valid, dx, jnp.zeros_like(dx))
        dpsum = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(b_valid, g,
                                           jnp.zeros_like(g)
                                           ).astype(acc.dtype),
            dpsum, dp)

        f_slot = m_fc % n_buf
        old = jax.lax.dynamic_index_in_dim(resid, f_slot, 0,
                                           keepdims=False)
        resid = jax.lax.dynamic_update_index_in_dim(
            resid, jnp.where(f_valid, f_inp, old), f_slot, 0)
        oldx = jax.lax.dynamic_index_in_dim(dxbuf, m_bc, 0,
                                            keepdims=False)
        dxbuf = jax.lax.dynamic_update_index_in_dim(
            dxbuf, jnp.where(b_valid & (s == 0), dx, oldx), m_bc, 0)

        act_next = jax.lax.ppermute(y, axis_name, fwd_perm)
        cot_next = jax.lax.ppermute(dx, axis_name, rev_perm)
        return (act_next, cot_next, resid, dpsum, dxbuf), None

    carry0 = (
        jnp.zeros((mb, t, c), xl.dtype),
        jnp.zeros((mb, t, c), dyl.dtype),
        jnp.zeros((n_buf, mb, t, c), xl.dtype),
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), local_params),
        jnp.zeros_like(dym),
    )
    (_, _, _, dpsum, dxbuf), _ = jax.lax.scan(
        tick, carry0, jnp.arange(2 * (M + S - 1)))
    # Stage 0 holds the real input-cotangents; replicate like the
    # forward's output buffer. dparams stay per-stage (out spec 'pipe')
    # but each data shard only saw ITS microbatches — and under SP x PP
    # each seq shard only its token slice — so sum the partial param
    # grads over 'data' AND (when sharded) the seq axis: exactly the
    # psums GPipe-AD's transpose inserts for every mesh axis the
    # params' in_spec replicates over but the cotangent varies over.
    # (dx needs no seq psum: its out_spec CARRIES the seq sharding.)
    # Under EP the unreduced-convention shares (see the dym / epn note)
    # complete here too: psum over ep for dx and for every leaf NOT
    # sharded over the ep axis; ep-sharded leaves hold per-shard grads
    # and must not mix.
    dx_axes = ((axis_name,) if ep_axis is None
               else (axis_name, ep_axis))
    dx = jax.lax.psum(
        jnp.where(s == 0, dxbuf, jnp.zeros_like(dxbuf)), dx_axes)
    grad_axes = ((data_axis,) if seq_axis is None
                 else (data_axis, seq_axis))

    def leaf_axes(spec):
        if ep_axis is None or (spec is not None
                               and ep_axis in tuple(spec)):
            return grad_axes
        return grad_axes + (ep_axis,)

    # PartitionSpec is a tuple subclass (a pytree NODE), so flatten the
    # spec tree with is_leaf instead of a joint tree_map.
    flat_p, treedef = jax.tree_util.tree_flatten(local_params)
    flat_acc = jax.tree_util.tree_leaves(dpsum)
    if param_specs is None:
        flat_specs = [None] * len(flat_p)
    else:
        flat_specs = jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda v: isinstance(v, P))
    dparams = treedef.unflatten([
        jax.lax.psum(acc, leaf_axes(sp_)).astype(p.dtype)
        for acc, p, sp_ in zip(flat_acc, flat_p, flat_specs)])
    return dparams, dx.reshape(bl, t, c)


# ---------------------------------------------------------------------------
# Interleaved 1F1B: virtual pipeline stages (Megatron-style), manual VJP.
# ---------------------------------------------------------------------------
#
# Each device holds v model CHUNKS instead of one contiguous stage:
# global stage g = j * S + d lives on device d as its local chunk j
# (stacked params stay P('pipe')-sharded; the contiguous local slice is
# REINTERPRETED as [v, layers/chunk] — the chunk-permuted storage order,
# interleaved_layer_order). Activations hop a FULL ring (wraparound
# (S-1) -> 0 carries chunk j's output into chunk j+1).
#
# Why: the non-interleaved schedules' bubble fraction is
# (S-1)/(M+S-1) regardless of schedule (gpipe == 1f1b there). Counting
# in CHUNK-ticks (1 chunk = 1/v of a device's layers — the honest unit
# when comparing against v chunks/device), a non-interleaved step costs
# 2v(M + S - 1) chunk-ticks; the interleaved forward is a dense
# closed-form circular pipeline finishing in vM + S - 1, and the
# combined replay/backward table measures ~2vM + O(vS) — bubble
# fraction ~(S-1)/(vM), the ~v-fold reduction (Narayanan et al. 2021).
# Measured tables (tests/test_pp_interleaved.py): the schedule-table
# bubble and the XLA memory analysis quantify bubble x memory against
# gpipe/1f1b.
#
# The backward is a hand-written custom_vjp like onef1b: one combined
# scan replays chunk forwards and runs chunk backwards in Megatron's
# warmup / one-F-one-B / cooldown order. Unlike onef1b's closed-form
# tick table, hop slack here is NOT uniformly 1 (steady-state F and B
# streams cross devices with phase offsets), so the schedule is built
# HOST-SIDE by a greedy list scheduler (interleaved_bwd_schedule) that
# also performs interval allocation for three bounded ring buffers —
# saved chunk inputs (residuals) and in-flight F/B arrivals — and the
# device-side scan just indexes the resulting [T, S] tables. Residency
# stays O(S*v + slack) chunk inputs per device (measured in the memory
# test), not gpipe-AD's O(M) stacked per-tick internals.
#
# Scope (fail-loud): no with_aux/MoE, no seq sharding, no extra/packed
# metadata — compose those with gpipe/1f1b; interleaved's contribution
# is the bubble. Requires n_micro % S == 0 (Megatron's constraint: the
# F-stream cycles chunks per S-microbatch group) and layers % (S*v) == 0.


def interleaved_layer_order(L: int, S: int, v: int) -> list:
    """``order[storage_idx] = semantic layer`` for the chunk-permuted
    stacking: device d's contiguous P('pipe') slice holds chunks
    d, S+d, 2S+d, ... (global stage g = j*S + d), so storage position
    d*(v*lc) + j*lc + o carries semantic layer (j*S + d)*lc + o."""
    if L % (S * v):
        raise ValueError(f"{L} layers not divisible by {S} stages x "
                         f"{v} virtual chunks")
    lc = L // (S * v)
    order = []
    for d in range(S):
        for j in range(v):
            g = j * S + d
            order.extend(range(g * lc, (g + 1) * lc))
    return order


def interleaved_fwd_schedule(S: int, M: int, v: int) -> list:
    """The closed-form dense forward table: ``table[t][d]`` is
    ``("F", m, j)`` or None. Device d runs its k-th chunk-op at tick
    d + k with k enumerating (microbatch-group, chunk, in-group
    microbatch): k = (m // S)*S*v + j*S + (m % S). Every hop
    (d -> d+1, and the (S-1) -> 0 wrap into the next chunk) lands with
    slack exactly 1, so the forward needs no arrival buffering and
    finishes in vM + S - 1 ticks."""
    if M % S:
        raise ValueError(f"interleaved needs microbatches ({M}) "
                         f"divisible by stages ({S})")
    n = v * M
    table = [[None] * S for _ in range(n + S - 1)]
    for d in range(S):
        for k in range(n):
            r, kk = divmod(k, S * v)
            j, i = divmod(kk, S)
            table[d + k][d] = ("F", r * S + i, j)
    return table


def _interleaved_oplist(S: int, M: int, v: int, d: int) -> list:
    """Device d's backward-scan op order (Megatron interleaved 1F1B):
    W(d) warmup chunk-forwards, then one-F-one-B, then B cooldown.
    F-stream order matches the forward schedule; the B stream is the
    same enumeration with chunks reversed (deepest chunk first)."""
    def fop(k):
        r, kk = divmod(k, S * v)
        j, i = divmod(kk, S)
        return ("F", r * S + i, j)

    def bop(b):
        r, bb = divmod(b, S * v)
        j, i = divmod(bb, S)
        return ("B", r * S + i, v - 1 - j)

    n = v * M
    W = min(n, 2 * (S - 1 - d) + (v - 1) * S)
    ops = [fop(k) for k in range(W)]
    f, b = W, 0
    while f < n:
        ops.append(fop(f)); f += 1
        ops.append(bop(b)); b += 1
    while b < n:
        ops.append(bop(b)); b += 1
    return ops


def _alloc_intervals(intervals):
    """Greedy interval-graph slot allocation: ``intervals`` is a list of
    (start, end, key) with inclusive occupancy [start, end]; returns
    ({key: slot}, n_slots)."""
    slots = {}
    free = []
    busy = []   # (end, slot) active
    n = 0
    for start, end, key in sorted(intervals):
        # release slots whose interval ended before this start
        still = []
        for e, sl in busy:
            if e < start:
                free.append(sl)
            else:
                still.append((e, sl))
        busy = still
        if free:
            sl = free.pop()
        else:
            sl = n
            n += 1
        busy.append((end, sl))
        slots[key] = sl
    return slots, max(n, 1)


def interleaved_bwd_schedule(S: int, M: int, v: int) -> dict:
    """Host-side greedy list scheduling of the combined replay/backward
    scan, plus buffer allocation. Returns numpy tables [T, S]:

    - kind (0 idle / 1 F / 2 B), m, j;
    - rs_save / rs_read: residual-ring slot an F-tick saves its chunk
      input into / a B-tick reads from (-1 none);
    - af_save / ab_save: arrival-ring slot to store THIS tick's
      ppermute delivery into (-1 discard) — hop slack can exceed 1, so
      deliveries wait in per-device rings until their consumer tick;
    - af_read / ab_read: arrival slot an F/B-tick reads its input
      cotangent/activation from (-1 = boundary: xm / dy);

    and scalars n_resid / n_arr_f / n_arr_b / n_ticks. Dependencies
    (producer tick + 1 <= consumer tick, F-before-its-B) are enforced
    during construction; the property tests re-verify independently."""
    import numpy as np
    if M % S:
        raise ValueError(f"interleaved needs microbatches ({M}) "
                         f"divisible by stages ({S})")
    n = v * M
    ops = [_interleaved_oplist(S, M, v, d) for d in range(S)]
    for d in range(S):   # F(m, j) precedes B(m, j) on every device
        pos = {op: i for i, op in enumerate(ops[d])}
        for (kind, m, j), i in pos.items():
            if kind == "B":
                assert pos[("F", m, j)] < i, (d, m, j)
    ptr = [0] * S
    done_f, done_b = {}, {}
    rows = []
    t = 0
    while any(p < len(o) for p, o in zip(ptr, ops)):
        row = [None] * S
        for d in range(S):
            if ptr[d] >= len(ops[d]):
                continue
            kind, m, j = ops[d][ptr[d]]
            if kind == "F":
                if d > 0:
                    ready = done_f.get((d - 1, m, j))
                elif j > 0:
                    ready = done_f.get((S - 1, m, j - 1))
                else:
                    ready = -1                      # xm always there
            else:
                own = done_f.get((d, m, j))
                if d < S - 1:
                    up = done_b.get((d + 1, m, j))
                elif j < v - 1:
                    up = done_b.get((0, m, j + 1))
                else:
                    up = -1                         # dy always there
                ready = (None if own is None or up is None
                         else max(own, up))
            if ready is not None and t >= ready + 1:
                row[d] = (kind, m, j)
        if all(r is None for r in row):
            raise RuntimeError(
                f"interleaved schedule deadlock at tick {t} "
                f"(S={S}, M={M}, v={v})")
        for d in range(S):
            if row[d] is not None:
                kind, m, j = row[d]
                (done_f if kind == "F" else done_b)[(d, m, j)] = t
                ptr[d] += 1
        rows.append(row)
        t += 1
    T = len(rows)

    kind = np.zeros((T, S), np.int32)
    mi = np.zeros((T, S), np.int32)
    ji = np.zeros((T, S), np.int32)
    rs_save = -np.ones((T, S), np.int32)
    rs_read = -np.ones((T, S), np.int32)
    af_save = -np.ones((T, S), np.int32)
    af_read = -np.ones((T, S), np.int32)
    ab_save = -np.ones((T, S), np.int32)
    ab_read = -np.ones((T, S), np.int32)
    for t, row in enumerate(rows):
        for d, op in enumerate(row):
            if op is None:
                continue
            kind[t, d] = 1 if op[0] == "F" else 2
            mi[t, d] = op[1]
            ji[t, d] = op[2]

    n_res = n_af = n_ab = 1
    for d in range(S):
        # residuals: input saved at F(m, j), read at B(m, j)
        iv = [(done_f[(d, m, j)], done_b[(d, m, j)], (m, j))
              for m in range(M) for j in range(v)]
        sl, nr = _alloc_intervals(iv)
        n_res = max(n_res, nr)
        for (m, j), s_ in sl.items():
            rs_save[done_f[(d, m, j)], d] = s_
            rs_read[done_b[(d, m, j)], d] = s_
        # F arrivals: produced upstream at tp, stored here at tp+1,
        # read at this device's F tick
        iv = []
        for m in range(M):
            for j in range(v):
                if d > 0:
                    tp = done_f[(d - 1, m, j)]
                elif j > 0:
                    tp = done_f[(S - 1, m, j - 1)]
                else:
                    continue                        # from xm
                iv.append((tp + 1, done_f[(d, m, j)], (m, j)))
        if iv:
            sl, na = _alloc_intervals(iv)
            n_af = max(n_af, na)
            for (m, j), s_ in sl.items():
                iv_start = [x for x in iv if x[2] == (m, j)][0][0]
                af_save[iv_start, d] = s_
                af_read[done_f[(d, m, j)], d] = s_
        # B arrivals: cotangent produced downstream at tp
        iv = []
        for m in range(M):
            for j in range(v):
                if d < S - 1:
                    tp = done_b[(d + 1, m, j)]
                elif j < v - 1:
                    tp = done_b[(0, m, j + 1)]
                else:
                    continue                        # from dy
                iv.append((tp + 1, done_b[(d, m, j)], (m, j)))
        if iv:
            sl, nb = _alloc_intervals(iv)
            n_ab = max(n_ab, nb)
            for (m, j), s_ in sl.items():
                iv_start = [x for x in iv if x[2] == (m, j)][0][0]
                ab_save[iv_start, d] = s_
                ab_read[done_b[(d, m, j)], d] = s_
    return dict(kind=kind, m=mi, j=ji, rs_save=rs_save, rs_read=rs_read,
                af_save=af_save, af_read=af_read, ab_save=ab_save,
                ab_read=ab_read, n_resid=n_res, n_arr_f=n_af,
                n_arr_b=n_ab, n_ticks=T)


def interleaved(stage_apply: Callable, stacked_params, x, *,
                mesh: Mesh, n_micro: int, n_virtual: int = 2,
                axis_name: str = "pipe", data_axis: str = "data",
                key=None, extra=None, with_aux: bool = False,
                param_specs=None, ep_axis: str = None):
    """Interleaved-1F1B pipeline executor (module section comment).

    Contract differs from gpipe/onef1b in ONE way: ``stage_apply``
    receives a CHUNK's params — leading dim layers/(S*v) — instead of
    a stage's, with ``key`` (when given) already folded per
    (microbatch, global stage); the chunk body folds per local layer.
    ``stacked_params`` leaves are the usual [L, ...] stacks sharded
    P('pipe'), REINTERPRETED chunk-permuted (interleaved_layer_order):
    callers that assign semantic meaning to stack positions (unstack
    converters, sequential fallbacks) must apply the permutation.
    ``extra`` matches gpipe's contract (per-microbatch metadata, e.g.
    packed segment ids — every chunk-op indexes its microbatch's
    slice, treated as non-differentiable; stage protocol becomes
    ``stage_apply(chunk_params, x, extra_micro[, key])``).
    ``with_aux`` matches gpipe's too (chunk returns (y, aux); the
    executor returns (out, aux_total) = sum over chunk-ops, mean over
    microbatches and data shards). ``param_specs`` / ``ep_axis``
    (MoE/EP x interleaved): per-leaf spec overrides (expert stacks
    P('pipe','model')) and the mesh axis the chunk bodies' expert
    collectives run over — with ``ep_axis`` the backward switches to
    the collective-uniform one-vjp-per-tick form (in-stage
    collectives inside the diverging F/B cond corrupt gradients,
    onef1b's documented trap) and speaks onef1b's
    unreduced-cotangent convention (entering cotangent divided by
    the axis size, every leaf completed at the end per its spec).
    No seq_axis support (compose SP with gpipe/1f1b)."""
    S = mesh.shape[axis_name]
    v = n_virtual
    if v < 2:
        raise ValueError(f"interleaved needs n_virtual >= 2 chunks "
                         f"per device (got {v}); use gpipe/1f1b at "
                         "v=1")
    if S == 1:
        raise ValueError("interleaved needs a 'pipe' mesh axis > 1 "
                         "(the sequential fallback would have to "
                         "un-permute the chunk storage; use "
                         "gpipe/1f1b at pipe=1)")
    if n_micro % S:
        raise ValueError(f"interleaved needs n_micro ({n_micro}) "
                         f"divisible by the pipe axis ({S}) — the "
                         "F-stream cycles chunks per S-microbatch "
                         "group")
    for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
        if leaf.shape[0] % (S * v):
            raise ValueError(
                f"stacked param {jax.tree_util.keystr(path)} leading "
                f"dim {leaf.shape[0]} not divisible by {S} stages x "
                f"{v} chunks")

    sched = interleaved_bwd_schedule(S, n_micro, v)
    p_specs = (param_specs if param_specs is not None else
               jax.tree_util.tree_map(lambda _: P(axis_name),
                                      stacked_params))
    x_spec = P(data_axis, None, None)
    keyed = key is not None
    kk = key if keyed else jnp.zeros((2,), jnp.uint32)
    has_extra = extra is not None
    ex = extra if has_extra else jnp.zeros((0,), jnp.int32)
    e_spec = P(data_axis) if has_extra else P()
    kw = dict(n_micro=n_micro, n_virtual=v, n_stages=S,
              axis_name=axis_name, data_axis=data_axis, keyed=keyed,
              has_extra=has_extra, with_aux=with_aux, ep_axis=ep_axis)
    fwd_out_specs = (x_spec, P()) if with_aux else x_spec

    def fwd_program(params, xx, exx, k):
        body = functools.partial(_ileave_fwd_body, stage_apply, **kw)
        return shard_map(
            body, mesh=mesh, in_specs=(p_specs, x_spec, e_spec, P()),
            out_specs=fwd_out_specs, check_vma=False)(params, xx, exx, k)

    def bwd_program(params, xx, exx, k, dy, daux):
        body = functools.partial(_ileave_bwd_body, stage_apply,
                                 sched=sched, param_specs=p_specs,
                                 **kw)
        return shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, x_spec, e_spec, P(), x_spec, P()),
            out_specs=(p_specs, x_spec), check_vma=False)(
                params, xx, exx, k, dy, daux)

    @jax.custom_vjp
    def run(params, xx, exx, k):
        return fwd_program(params, xx, exx, k)

    def run_fwd(params, xx, exx, k):
        return fwd_program(params, xx, exx, k), (params, xx, exx, k)

    def run_bwd(res, ct):
        params, xx, exx, k = res
        if with_aux:
            dy, daux = ct
        else:
            dy, daux = ct, jnp.zeros((), jnp.float32)
        dparams, dx = bwd_program(params, xx, exx, k, dy,
                                  daux.astype(jnp.float32))
        dk = np.zeros(np.shape(k), dtype=jax.dtypes.float0)
        dex = (np.zeros(np.shape(exx), dtype=jax.dtypes.float0)
               if jnp.issubdtype(exx.dtype, jnp.integer)
               else jnp.zeros_like(exx))
        return dparams, dx, dex, dk

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, x, ex, kk)


def _ileave_chunks(local_params, v):
    """Reinterpret the local [L/S, ...] stack as [v, lc, ...] chunks."""
    return jax.tree_util.tree_map(
        lambda p: p.reshape((v, p.shape[0] // v) + p.shape[1:]),
        local_params)


def _ileave_chunk_params(chunks, j):
    """Chunk j's param slice out of the [v, lc, ...] local stacks."""
    return jax.tree_util.tree_map(
        lambda p: jax.lax.dynamic_index_in_dim(p, j, 0, keepdims=False),
        chunks)


def _ileave_run(stage_apply, cp, x, m, g, key, keyed, em=None):
    """Apply one chunk with the key folded per (microbatch, global
    stage). The ONE fold location: forward body, backward replay and
    the backward's vjp'd function all route through here, so replayed
    dropout masks match the primal bit-for-bit by construction.
    ``em`` ([M, mb, ...] microbatched extra metadata): this
    microbatch's slice is indexed here, keeping the extra protocol
    in one place too."""
    args = (cp, x)
    if em is not None:
        args += (jax.lax.dynamic_index_in_dim(em, m, 0,
                                              keepdims=False),)
    if keyed:
        k = jax.random.fold_in(jax.random.fold_in(key, m), g)
        return stage_apply(*args, k)
    return stage_apply(*args)


def _ileave_apply(stage_apply, chunks, j, x, m, s, S, key, keyed,
                  em=None):
    """Index chunk j and run it (see _ileave_run)."""
    cp = _ileave_chunk_params(chunks, j)
    return cp, _ileave_run(stage_apply, cp, x, m, j * S + s, key,
                           keyed, em)


def _ileave_fwd_body(stage_apply, local_params, xl, exl, key, *,
                     n_micro, n_virtual, n_stages, axis_name,
                     data_axis, keyed, has_extra=False, with_aux=False,
                     ep_axis=None):
    """Dense circular forward: vM + S - 1 ticks, closed-form indices
    (interleaved_fwd_schedule), full-ring ppermute each tick. With
    ``with_aux`` each chunk-op's scalar accumulates; the total is the
    sum over all (device, chunk) ops and the mean over microbatches
    and data shards — gpipe's aux semantics."""
    s = jax.lax.axis_index(axis_name)
    S, M, v = n_stages, n_micro, n_virtual
    bl, t, c = xl.shape
    if bl % M:
        raise ValueError(f"local batch {bl} not divisible by "
                         f"{M} microbatches")
    mb = bl // M
    xm = xl.reshape(M, mb, t, c)
    em = (exl.reshape((M, mb) + exl.shape[1:]) if has_extra else None)
    chunks = _ileave_chunks(local_params, v)
    ring = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t_):
        act_in, outbuf, auxsum = carry
        k = t_ - s
        valid = (k >= 0) & (k < v * M)
        kc = jnp.clip(k, 0, v * M - 1)
        kk = kc % (S * v)
        m = (kc // (S * v)) * S + (kk % S)
        j = kk // S
        inp = jnp.where((s == 0) & (j == 0),
                        jax.lax.dynamic_index_in_dim(xm, m, 0,
                                                     keepdims=False),
                        act_in)
        _, y = _ileave_apply(stage_apply, chunks, j, inp, m, s, S,
                             key, keyed, em)
        if with_aux:
            y, a = y
            auxsum = auxsum + jnp.where(valid,
                                        a.astype(jnp.float32), 0.0)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        is_out = valid & (s == S - 1) & (j == v - 1)
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf,
            jnp.where(is_out, y,
                      jax.lax.dynamic_index_in_dim(outbuf, m, 0,
                                                   keepdims=False)),
            m, 0)
        return (jax.lax.ppermute(y, axis_name, ring), outbuf,
                auxsum), None

    act0 = jnp.zeros((mb, t, c), xl.dtype)
    (_, outbuf, auxsum), _ = jax.lax.scan(
        tick, (act0, jnp.zeros_like(xm), jnp.zeros((), jnp.float32)),
        jnp.arange(v * M + S - 1))
    outbuf = jax.lax.psum(
        jnp.where(s == S - 1, outbuf, jnp.zeros_like(outbuf)),
        axis_name)
    out = outbuf.reshape(bl, t, c)
    if not with_aux:
        return out
    n_data = jax.lax.psum(1, data_axis)
    aux = jax.lax.psum(jax.lax.psum(auxsum, axis_name), data_axis)
    return out, aux / (M * n_data)


def _ileave_bwd_body(stage_apply, local_params, xl, exl, key, dyl,
                     dauxl=None, *, sched, n_micro, n_virtual,
                     n_stages, axis_name, data_axis, keyed,
                     has_extra=False, with_aux=False, ep_axis=None,
                     param_specs=None):
    """Combined replay/backward scan over the host-built table: per
    tick, store ring-delivered arrivals into their allocated slots,
    run this device's op (F replay saving its input to the residual
    ring, or B vjp-ing the saved input against the arrived cotangent),
    and ppermute both streams around the full ring. With ``ep_axis``
    the chunk bodies contain expert collectives, so every tick runs
    ONE vjp on a role-selected input (collective-uniform; the F/B
    cond's diverging collectives corrupt gradients — onef1b's
    documented trap) and the scan speaks the unreduced-cotangent
    convention: entering cotangents divided by the axis size, every
    leaf completed at the end per its spec (onef1b's ep notes)."""
    s = jax.lax.axis_index(axis_name)
    S, M, v = n_stages, n_micro, n_virtual
    bl, t, c = xl.shape
    mb = bl // M
    xm = xl.reshape(M, mb, t, c)
    em = (exl.reshape((M, mb) + exl.shape[1:]) if has_extra else None)
    dym = dyl.reshape(M, mb, t, c)
    epn = jax.lax.psum(1, ep_axis) if ep_axis is not None else 1
    if ep_axis is not None:
        dym = dym / epn          # sums-to-truth shares (onef1b note)
    if with_aux:
        n_data = jax.lax.psum(1, data_axis)
        aux_ct = dauxl.astype(jnp.float32) / (M * n_data * epn)
    uniform = ep_axis is not None
    chunks = _ileave_chunks(local_params, v)
    fwd_ring = [(i, (i + 1) % S) for i in range(S)]
    bwd_ring = [((i + 1) % S, i) for i in range(S)]
    tbl = jax.tree_util.tree_map(
        jnp.asarray, {k_: sched[k_] for k_ in
                      ("kind", "m", "j", "rs_save", "rs_read",
                       "af_save", "af_read", "ab_save", "ab_read")})

    def store(buf, slot, val):
        cur = jax.lax.dynamic_index_in_dim(
            buf, jnp.maximum(slot, 0), 0, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            buf, jnp.where(slot >= 0, val, cur), jnp.maximum(slot, 0), 0)

    def load(buf, slot):
        return jax.lax.dynamic_index_in_dim(
            buf, jnp.maximum(slot, 0), 0, keepdims=False)

    def tick(carry, row):
        act_in, cot_in, arr_f, arr_b, resid, dpsum, dxbuf = carry
        col = {k_: row[k_][s] for k_ in row}
        kind, m, j = col["kind"], col["m"], col["j"]
        is_f, is_b = kind == 1, kind == 2
        # 1. bank this tick's ring deliveries
        arr_f = store(arr_f, col["af_save"], act_in)
        arr_b = store(arr_b, col["ab_save"], cot_in)
        # 2. inputs
        x_f = jnp.where((s == 0) & (j == 0) & (col["af_read"] < 0),
                        jax.lax.dynamic_index_in_dim(xm, m, 0,
                                                     keepdims=False),
                        load(arr_f, col["af_read"]))
        x_b = load(resid, col["rs_read"])
        g_in = jnp.where((s == S - 1) & (j == v - 1)
                         & (col["ab_read"] < 0),
                         jax.lax.dynamic_index_in_dim(dym, m, 0,
                                                      keepdims=False),
                         load(arr_b, col["ab_read"]))
        # 3. the op. Without ep collectives the cheap cond schedule
        # runs only the branch each tick needs (idle ticks land in
        # do_b on zeros, masked below — onef1b's trick); with them,
        # ONE vjp per tick on a role-selected input keeps the
        # collective sequence identical on every device every tick.
        zero_dp = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape[1:], p.dtype), chunks)

        def chunk_fn(c, xi):
            return _ileave_run(stage_apply, c, xi, m, j * S + s,
                               key, keyed, em)

        def pull_ct(pull):
            return pull((g_in, aux_ct) if with_aux else g_in)

        if uniform:
            inp = jnp.where(is_f, x_f, x_b)
            cp = _ileave_chunk_params(chunks, j)
            y, pull = jax.vjp(chunk_fn, cp, inp)
            if with_aux:
                y = y[0]
            dp, dx = pull_ct(pull)
        else:
            def do_f(_):
                _, y = _ileave_apply(stage_apply, chunks, j, x_f, m,
                                     s, S, key, keyed, em)
                if with_aux:
                    y = y[0]
                return y, jnp.zeros_like(x_f), zero_dp

            def do_b(_):
                cp = _ileave_chunk_params(chunks, j)
                _, pull = jax.vjp(chunk_fn, cp, x_b)
                dp, dx = pull_ct(pull)
                return jnp.zeros_like(x_b), dx, dp

            y, dx, dp = jax.lax.cond(is_f, do_f, do_b, None)
        y = jnp.where(is_f, y, jnp.zeros_like(y))
        dx = jnp.where(is_b, dx, jnp.zeros_like(dx))
        # 4. bookkeeping
        resid = store(resid, jnp.where(is_f, col["rs_save"], -1), x_f)
        dpsum = jax.tree_util.tree_map(
            lambda acc, g_: jax.lax.dynamic_update_index_in_dim(
                acc,
                jax.lax.dynamic_index_in_dim(acc, j, 0, keepdims=False)
                + jnp.where(is_b, g_, jnp.zeros_like(g_)
                            ).astype(acc.dtype),
                j, 0),
            dpsum, dp)
        oldx = jax.lax.dynamic_index_in_dim(dxbuf, m, 0, keepdims=False)
        dxbuf = jax.lax.dynamic_update_index_in_dim(
            dxbuf, jnp.where(is_b & (s == 0) & (j == 0), dx, oldx),
            m, 0)
        return (jax.lax.ppermute(y, axis_name, fwd_ring),
                jax.lax.ppermute(dx, axis_name, bwd_ring),
                arr_f, arr_b, resid, dpsum, dxbuf), None

    shp = (mb, t, c)
    carry0 = (
        jnp.zeros(shp, xl.dtype),
        jnp.zeros(shp, dyl.dtype),
        jnp.zeros((sched["n_arr_f"],) + shp, xl.dtype),
        jnp.zeros((sched["n_arr_b"],) + shp, dyl.dtype),
        jnp.zeros((sched["n_resid"],) + shp, xl.dtype),
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), chunks),
        jnp.zeros_like(dym),
    )
    (_, _, _, _, _, dpsum, dxbuf), _ = jax.lax.scan(
        tick, carry0, tbl)
    # Stage-0 holds the real input cotangents; with ep the unreduced
    # shares complete over the ep axis too (dx is ep-replicated).
    dx_axes = ((axis_name,) if ep_axis is None
               else (axis_name, ep_axis))
    dx = jax.lax.psum(
        jnp.where(s == 0, dxbuf, jnp.zeros_like(dxbuf)), dx_axes)
    # Chunk grads back to the [L/S, ...] stack; each data shard saw
    # only its microbatches -> complete over 'data', and under ep over
    # the ep axis for every leaf NOT sharded over it (ep-sharded
    # expert stacks hold per-shard grads and must not mix) — exactly
    # onef1b's leaf rule.
    flat_p, treedef = jax.tree_util.tree_flatten(local_params)
    flat_acc = jax.tree_util.tree_leaves(dpsum)
    if param_specs is None or ep_axis is None:
        flat_specs = [None] * len(flat_p)
    else:
        flat_specs = jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda vv: isinstance(vv, P))

    def leaf_axes(spec):
        if ep_axis is None or (spec is not None
                               and ep_axis in tuple(spec)):
            return (data_axis,)
        return (data_axis, ep_axis)

    dparams = treedef.unflatten([
        jax.lax.psum(
            acc.reshape((acc.shape[0] * acc.shape[1],)
                        + acc.shape[2:]),
            leaf_axes(sp_)).astype(p.dtype)
        for acc, p, sp_ in zip(flat_acc, flat_p, flat_specs)])
    return dparams, dx.reshape(bl, t, c)
