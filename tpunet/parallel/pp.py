"""Pipeline parallelism: GPipe-style SPMD executor over the 'pipe' axis.

The reference has no pipeline parallelism (single-file model, SURVEY.md
2b); tpunet implements it the TPU way: no per-stage processes, no
send/recv threads — ONE jitted SPMD program in which every device runs
the same code, holds one pipeline stage's worth of stacked layer
parameters (leading dim sharded over 'pipe'), and activations hop
stage-to-stage with ``lax.ppermute`` (one ICI neighbor hop per tick).

Schedule: plain GPipe with M microbatches over S stages; the static
scan runs M + S - 1 ticks. At tick t, stage s computes microbatch
m = t - s (masked out when m is out of range — idle bubble ticks
compute on zeros and are discarded). Stage 0 reads microbatches from
the (replicated) input; stage S-1 accumulates results into the output
buffer, which a final psum over 'pipe' replicates (all other stages
contribute zeros).

Differentiable end-to-end: reverse-mode AD through scan + ppermute
yields the standard backward pipeline (the transpose of a shifted
ppermute is the reverse shift).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def gpipe(stage_apply: Callable, stacked_params, x, *,
          mesh: Mesh, n_micro: int, axis_name: str = "pipe",
          data_axis: str = "data", key=None):
    """Run ``x`` through all pipeline stages.

    stage_apply(local_params, x_micro) applies one stage's layer stack
    to one microbatch; it is called inside shard_map, where every leaf
    of ``local_params`` is the device-local slice (leading dim
    total_layers/S) of ``stacked_params``.

    ``key`` (optional PRNG key) enables stochastic stages (dropout):
    stage_apply is then called as stage_apply(local_params, x_micro,
    key) with a key folded per (tick, stage) — unique randomness per
    microbatch per stage, identical math under AD.

    x: [B, T, C] (batch sharded over ``data_axis``); returns [B, T, C].
    """
    n_stages = mesh.shape[axis_name]
    if n_stages == 1:
        return (stage_apply(stacked_params, x) if key is None
                else stage_apply(stacked_params, x, key))

    for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
        if leaf.shape[0] % n_stages:
            raise ValueError(
                f"stacked param {jax.tree_util.keystr(path)} has leading "
                f"(layer) dim {leaf.shape[0]} not divisible by "
                f"{n_stages} pipeline stages")

    p_specs = jax.tree_util.tree_map(lambda _: P(axis_name), stacked_params)
    x_spec = P(data_axis, None, None)

    if key is None:
        body = functools.partial(_gpipe_body, stage_apply,
                                 n_micro=n_micro, axis_name=axis_name)
        in_specs = (p_specs, x_spec)
        args = (stacked_params, x)
    else:
        body = functools.partial(_gpipe_body_keyed, stage_apply,
                                 n_micro=n_micro, axis_name=axis_name)
        in_specs = (p_specs, x_spec, P())      # key replicated
        args = (stacked_params, x, key)

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=x_spec,
        check_vma=False)
    return fn(*args)


def _gpipe_body_keyed(stage_apply, local_params, xl, key, *, n_micro,
                      axis_name):
    """_gpipe_body with a per-(tick, stage) folded PRNG key."""
    s = jax.lax.axis_index(axis_name)

    def keyed_apply(params, x, step):
        return stage_apply(params, x,
                           jax.random.fold_in(jax.random.fold_in(key,
                                                                 step), s))

    return _gpipe_body(keyed_apply, local_params, xl, n_micro=n_micro,
                       axis_name=axis_name, pass_step=True)


def _gpipe_body(stage_apply, local_params, xl, *, n_micro, axis_name,
                pass_step=False):
    s = jax.lax.axis_index(axis_name)
    n_stages = jax.lax.psum(1, axis_name)
    bl, t, c = xl.shape
    if bl % n_micro:
        raise ValueError(f"local batch {bl} not divisible by "
                         f"{n_micro} microbatches")
    mb = bl // n_micro
    xm = xl.reshape(n_micro, mb, t, c)
    perm = [(i, i + 1) for i in range(n_stages - 1)]  # no wraparound

    def tick(carry, step):
        act_in, outbuf = carry
        m = step - s
        valid = (m >= 0) & (m < n_micro)
        mc = jnp.clip(m, 0, n_micro - 1)
        inp = jnp.where(s == 0,
                        jax.lax.dynamic_index_in_dim(xm, mc, 0,
                                                     keepdims=False),
                        act_in)
        y = (stage_apply(local_params, inp, step) if pass_step
             else stage_apply(local_params, inp))
        y = jnp.where(valid, y, jnp.zeros_like(y))
        is_last = s == n_stages - 1
        outbuf = jax.lax.dynamic_update_index_in_dim(
            outbuf,
            jnp.where(valid & is_last, y,
                      jax.lax.dynamic_index_in_dim(outbuf, mc, 0,
                                                   keepdims=False)),
            mc, 0)
        act_next = jax.lax.ppermute(y, axis_name, perm)
        return (act_next, outbuf), None

    act0 = jnp.zeros((mb, t, c), xl.dtype)
    outbuf = jnp.zeros_like(xm)
    (_, outbuf), _ = jax.lax.scan(
        tick, (act0, outbuf), jnp.arange(n_micro + n_stages - 1))
    # Only the last stage wrote real activations; psum replicates them.
    outbuf = jax.lax.psum(
        jnp.where(s == n_stages - 1, outbuf, jnp.zeros_like(outbuf)),
        axis_name)
    return outbuf.reshape(bl, t, c)
