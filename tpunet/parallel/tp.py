"""Tensor parallelism: path-rule parameter partitioning over 'model'.

The reference replicates every parameter on every rank (README.md:77
"Model parameters remain consistent across all GPUs"); tpunet adds
tensor parallelism the XLA way: parameters are *sharded* over the mesh
'model' axis according to path rules, jit is given the resulting
shardings, and GSPMD inserts the all-gathers/reduce-scatters — the
semantics of the program are unchanged (same math, distributed layout),
so TP composes with data and sequence parallelism without touching the
model code.

Rules are (regex, PartitionSpec) pairs matched against 'a/b/c' joined
tree paths. Because optimizer moments (Adam mu/nu) mirror the param
tree, the same rules match inside ``opt_state`` too — sharding the
optimizer states alongside their parameters (what ZeRO does with
hand-rolled bookkeeping, here for free).

The ViT rules implement Megatron-style block sharding: qkv and mlp/fc1
are column-parallel (output features over 'model'), attn/out and
mlp/fc2 are row-parallel (input features over 'model') — one reduce
per block pair, inserted by the compiler.
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpunet.config import ModelConfig

Rules = Sequence[Tuple[str, P]]

# Megatron-style ViT sharding (tpunet/models/vit.py module names), plus
# expert parallelism: MoE expert params ([E, ...]) shard their expert
# dim over 'model' (tpunet/models/moe.py; the router stays replicated).
VIT_TP_RULES: Rules = (
    (r"attn/qkv/kernel$", P(None, "model")),      # column parallel
    (r"attn/qkv/bias$", P("model")),
    (r"attn/out/kernel$", P("model", None)),      # row parallel
    (r"mlp/fc1/kernel$", P(None, "model")),       # column parallel
    (r"mlp/fc1/bias$", P("model")),
    (r"mlp/fc2/kernel$", P("model", None)),       # row parallel
    (r"moe/wi$", P("model", None, None)),         # expert parallel
    (r"moe/bi$", P("model", None)),
    (r"moe/wo$", P("model", None, None)),
    (r"moe/bo$", P("model", None)),
)


# Pipeline-parallel models (vit_pp, lm_pp): every stacked block param
# ([depth, ...]) shards its leading layer dim over 'pipe' — contiguous
# chunks, i.e. one stage's layers per device. MoE expert stacks
# ([G, E, ...]) additionally shard their expert dim over 'model'
# (EP x PP, tpunet/models/lm_pp.py; the router stacks stay replicated
# over 'model' — routing is computed on every expert shard). Listed
# BEFORE the catch-all so the more specific rule wins.
VIT_PP_RULES: Rules = (
    (r"blocks_moe_(wi|bi|wo|bo)$", P("pipe", "model")),
    (r"blocks_\w+$", P("pipe")),
)


def pp_stack_spec(param_name: str) -> P:
    """The VIT_PP_RULES spec for one stacked param name — the shared
    source of truth the pipelined models use to build their executors'
    ``param_specs``, so the executor's shard_map in_specs can never
    drift from how the Trainer stores the params."""
    for rx, spec in VIT_PP_RULES:
        if re.search(rx, param_name):
            return spec
    return P("pipe")


# ZeRO-1: Adam moments shard their leading dim over 'data'; params stay
# replicated (the reference's layout). Listed AFTER the model rules, so
# TP/PP-matched moments keep their parameter's sharding and only the
# rest (embeddings, norms, biases, conv kernels with a divisible lead
# dim) spread over the data axis.
ZERO1_RULES: Rules = (
    (r"(^|/)(mu|nu)/", P("data")),
)


# FSDP / ZeRO-3: parameters AND their Adam moments shard over 'data'.
# The spec is the FSDP sentinel, resolved per leaf: the largest dim
# divisible by the data-axis size is sharded (conv kernels are HWIO, so
# the useful dim is a channel dim, not dim 0; dense kernels shard
# whichever of in/out features is bigger). The RESIDENT state — params
# and both Adam moments — is 1/N per device; the train step all-gathers
# the params once at its start and computes replicated (see
# _steps_from_micro in tpunet/train/steps.py: left to sharding
# propagation instead, GSPMD pushes weight shards into attention
# activations and falls back to involuntary full-rematerialization
# reshards), while the Adam update itself runs on the 1/N moment
# shards. batch_stats and the step counter stay replicated. Listed
# AFTER the model rules, so TP/PP leaves keep their model-axis sharding.
FSDP = "FSDP"  # sentinel: resolve spec per leaf (largest divisible dim)

FSDP_RULES: Rules = (
    (r"^params/", FSDP),
    # EMA params mirror the param tree shape-for-shape, so they get the
    # identical per-leaf spec; state.replace(params=ema_params) at eval
    # time then matches the jitted eval step's in_shardings exactly.
    (r"^ema_params/", FSDP),
    (r"(^|/)(mu|nu)/", FSDP),
)


def _fsdp_spec(leaf, mesh: Mesh) -> P:
    n = mesh.shape.get("data", 1)
    shape = getattr(leaf, "shape", ())
    if n <= 1 or not shape:
        return P()
    best = max((d for d in range(len(shape)) if shape[d] % n == 0),
               key=lambda d: shape[d], default=None)
    if best is None or shape[best] < n:
        return P()
    return P(*([None] * best + ["data"]))


def rules_for(cfg: ModelConfig, mesh: Mesh = None,
              zero1: bool = False, fsdp: bool = False) -> Rules:
    """Sharding rules for the configured model. MobileNetV2 params stay
    replicated — at 2.2M params a CNN gains nothing from weight sharding
    (the reference's replicated layout is already right for it).

    ``mesh`` prunes rules whose axes have size 1 (no-op shardings would
    otherwise shadow the ZeRO-1/FSDP catch-alls for those leaves);
    ``zero1`` appends ZERO1_RULES; ``fsdp`` appends FSDP_RULES (which
    subsume ZeRO-1: moments follow their parameter's data-axis shard).
    """
    if cfg.name in ("vit_pp", "lm_pp"):
        rules = VIT_PP_RULES
    elif (cfg.name == "vit" or cfg.name.startswith("vit_")
          or cfg.name == "lm"):
        # The LM reuses the ViT encoder blocks, so the same Megatron
        # rules apply; embedding/positions stay replicated.
        rules = VIT_TP_RULES
    else:
        rules = ()
    if mesh is not None:
        rules = tuple(
            (rx, spec) for rx, spec in rules
            if all(mesh.shape.get(ax, 1) > 1
                   for ax in spec if ax is not None))
    if fsdp:
        rules = tuple(rules) + FSDP_RULES
    elif zero1:
        rules = tuple(rules) + ZERO1_RULES
    return rules


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
    return "/".join(parts)


def _spec_for(path_s: str, leaf, mesh: Mesh, rules) -> P:
    for rx, spec in rules:
        if rx.search(path_s) is None:
            continue
        if spec is FSDP or spec == FSDP:
            return _fsdp_spec(leaf, mesh)
        if len(spec) > getattr(leaf, "ndim", 0):
            continue  # rule doesn't fit this leaf; try later rules
        ok = True
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            # Skip the rule instead of crashing when the mesh lacks the
            # rule's axis (custom meshes) or the dim is indivisible —
            # later rules (e.g. the FSDP/ZeRO-1 catch-alls) still get a
            # chance; with none left the leaf replicates.
            if (axis not in mesh.shape
                    or leaf.shape[dim] % mesh.shape[axis] != 0):
                ok = False
                break
        if ok:
            return spec
    return P()


def tree_shardings(tree, mesh: Mesh, rules: Rules):
    """NamedSharding tree for ``tree``: rule-matched leaves are sharded,
    everything else replicated. Works on any pytree whose paths embed
    param names — TrainState included, so Adam moments inside opt_state
    pick up their parameter's spec automatically."""
    compiled = [(re.compile(rx), spec) for rx, spec in rules]
    return jax.tree_util.tree_map_with_path(
        lambda p, x: NamedSharding(mesh, _spec_for(_path_str(p), x, mesh,
                                                   compiled)),
        tree)


def state_shardings(state, model_cfg: ModelConfig, mesh: Mesh, *,
                    zero1: bool = False, fsdp: bool = False):
    """Shardings for laying a train state out on ``mesh`` — THE layout
    the Trainer pins as its steps' in/out shardings, factored here so
    the elastic restore path targets the identical function: restoring
    an FSDP checkpoint onto a resized mesh is ``restore_state`` with a
    target built by this on the NEW mesh, and every leaf (params, both
    Adam moments, EMA mirrors) re-shards to the new data axis because
    ``_fsdp_spec`` re-resolves per leaf against the new axis size."""
    return tree_shardings(
        state, mesh, rules_for(model_cfg, mesh=mesh, zero1=zero1,
                               fsdp=fsdp))
