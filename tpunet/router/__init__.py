"""Routing + autoscaling front tier over N serving replicas.

The serving engine (``tpunet/serve/``) is one replica: one KV-slot
pool, one ``/v1/generate`` endpoint. This package is the tier that
makes a *fleet* of them look like one endpoint — and acts on the
fleet signals the obs subsystems already produce:

- ``replica``    — per-replica handle: live queue-depth/slot
  occupancy probes (``/healthz`` + ``/metrics``), state machine
  (starting/healthy/draining/backoff/dead/evicted), failure streaks.
- ``balance``    — replica selection: least-loaded by probed load
  score, with session/prefix-affinity rendezvous hashing so
  shared-prompt traffic lands on the replica whose KV is warm.
- ``supervisor`` — replica lifecycle: spawns ``python -m
  tpunet.serve`` children, drain-then-restart (SIGTERM -> graceful
  drain -> SIGKILL), respawn with backoff.
- ``policy``     — hysteresis autoscale over fleet queue depth per
  slot and TTFT SLO burn.
- ``core``       — the Router: control loop (probe -> evict ->
  respawn -> scale -> emit), ``obs_router`` records, webhook-driven
  eviction (PR-9 ``AlertWebhook`` POSTs land on ``POST /webhook``).
- ``journal``    — bounded in-memory journal of in-flight streamed
  requests: the resume state mid-stream failover replays onto a
  surviving replica (docs/serving.md "Mid-stream failover &
  serve-tier chaos").
- ``frontend``   — stdlib threaded HTTP proxy: ``/v1/generate``
  (streaming and blocking, with mid-stream failover),
  ``/v1/classify``, ``/healthz``, ``/metrics``, ``/replicas``,
  ``/webhook``.

Cold-start is the autoscaling unlock: replicas boot with
``--aot-cache`` (tpunet/utils/cache.py ``AotProgramStore``) so a
scale-up or respawn serves in seconds, not a compile
(docs/serving.md "AOT warm-start").

Entry point: ``python -m tpunet.router`` (docs/serving.md
"Routing & autoscaling").
"""

from tpunet.router.balance import affinity_key, pick_replica
from tpunet.router.core import Router
from tpunet.router.frontend import RouterServer
from tpunet.router.journal import RequestJournal
from tpunet.router.policy import AutoscalePolicy
from tpunet.router.records import build_router_record
from tpunet.router.replica import ReplicaHandle
from tpunet.router.supervisor import Supervisor

__all__ = [
    "AutoscalePolicy", "ReplicaHandle", "RequestJournal", "Router",
    "RouterServer", "Supervisor", "affinity_key",
    "build_router_record", "pick_replica",
]
