"""CLI entry point: ``python -m tpunet.router``.

Two ways to get a fleet behind it:

- **external replicas** — point it at already-running servers::

      python -m tpunet.router --replica http://10.0.0.1:8000 \\
          --replica http://10.0.0.2:8000 --port 8100

  The router probes, routes, evicts, and emits scale decisions as
  *advice* (``obs_router`` events) — something else owns the
  processes.

- **supervisor mode** — the router owns the replica processes::

      python -m tpunet.router --spawn 2 --metrics-dir runs/router \\
          --aot-cache runs/router/aot -- \\
          --checkpoint-dir ckpt --slots 8 --prefill-buckets 64,256

  Everything after ``--`` is passed through to every ``python -m
  tpunet.serve`` child verbatim; per-child ``--port`` / ``--run-id``
  / ``--metrics-dir`` are appended by the supervisor, and
  ``--aot-cache`` is forwarded so respawns and scale-ups boot from
  the shared AOT program store in seconds.

SIGTERM/SIGINT drains: stop listening, stop the control loop, drain
every supervised child (in-flight streams finish), flush the final
``obs_router`` record.
"""

from __future__ import annotations

import signal
import sys


def build_argparser():
    import argparse

    from tpunet.config import RouterConfig

    d = RouterConfig()
    p = argparse.ArgumentParser(
        prog="python -m tpunet.router",
        description="tpunet routing + autoscaling front tier")
    p.add_argument("--replica", action="append", default=[],
                   metavar="URL",
                   help="external replica base URL (repeatable); "
                        "mutually composable with --spawn")
    p.add_argument("--spawn", type=int, default=0, metavar="N",
                   help="supervisor mode: launch N 'python -m "
                        "tpunet.serve' children (args after -- are "
                        "passed through to every child)")
    p.add_argument("--host", default=d.host)
    p.add_argument("--port", type=int, default=d.port)
    p.add_argument("--probe-interval-s", type=float,
                   default=d.probe_interval_s,
                   help="health/load probe cadence per replica")
    p.add_argument("--probe-timeout-s", type=float,
                   default=d.probe_timeout_s)
    p.add_argument("--unhealthy-after", type=int,
                   default=d.unhealthy_after,
                   help="consecutive probe failures before eviction")
    p.add_argument("--boot-timeout-s", type=float,
                   default=d.boot_timeout_s,
                   help="grace window after (re)spawn during which "
                        "probe failures don't count toward eviction")
    p.add_argument("--affinity-prefix", type=int,
                   default=d.affinity_prefix,
                   help="prompt tokens/bytes hashed for prefix "
                        "affinity (0 disables; 'session' field "
                        "always wins)")
    p.add_argument("--affinity-slack", type=float,
                   default=d.affinity_slack,
                   help="load-score margin the affinity replica may "
                        "exceed the least-loaded one by before "
                        "least-loaded wins")
    p.add_argument("--route-retries", type=int, default=d.route_retries,
                   help="re-route attempts when a replica fails "
                        "before any response byte was relayed")
    p.add_argument("--failover", default=d.failover,
                   action=argparse.BooleanOptionalAction,
                   help="mid-stream failover (default on): journal "
                        "streamed tokens and resume a dying stream "
                        "on a surviving replica with no error frame; "
                        "--no-failover restores the honest-error-"
                        "frame-and-client-retry behavior")
    p.add_argument("--failover-journal-tokens", type=int,
                   default=d.failover_journal_tokens,
                   help="per-stream journal bound: past this many "
                        "relayed tokens a stream is no longer "
                        "failover-protected (replica death then gets "
                        "the honest error frame)")
    p.add_argument("--failover-retries", type=int,
                   default=d.failover_retries,
                   help="resume attempts per request after mid-"
                        "stream replica deaths")
    p.add_argument("--chaos", default=d.chaos, metavar="SPEC",
                   help="serve-tier fault injection forwarded to "
                        "spawned replicas (tpunet/serve/chaos.py "
                        "grammar + ':replica=I' scope; unscoped "
                        "events reach every child) — the failover "
                        "matrix scripts/serve_chaos_smoke.py runs on")
    p.add_argument("--trace-sample", type=float,
                   default=d.trace_sample, metavar="RATE",
                   help="end-to-end request tracing head-sample rate "
                        "in [0,1] (tpunet/obs/tracing.py): sampled "
                        "requests carry X-Trace-Id to every replica "
                        "hop (failover re-submits included) and emit "
                        "obs_trace span records; a client-supplied "
                        "X-Trace-Id is always sampled")
    p.add_argument("--trace-all-on-error",
                   default=d.trace_all_on_error,
                   action=argparse.BooleanOptionalAction,
                   help="tail capture for unsampled requests "
                        "(default on): one router-hop obs_trace "
                        "record for any request that fails over or "
                        "errors, even below the sample rate")
    p.add_argument("--probe-every-s", type=float,
                   default=d.probe_every_s, metavar="S",
                   help="synthetic canary prober cadence (tpunet/"
                        "router/prober.py): issue a pinned greedy "
                        "known-answer request through the router's "
                        "own endpoint every S seconds, judging "
                        "availability/latency/bitwise-golden "
                        "correctness into the SLO engine's SLI "
                        "streams; every probe carries a minted "
                        "always-sampled X-Trace-Id (0 = off)")
    p.add_argument("--slo-policy", default=d.slo_policy,
                   metavar="FILE",
                   help="SLO policy JSON (docs/slos.json format; "
                        "full-line // comments ok): arms the "
                        "tpunet/obs/slo.py engine — obs_slo records, "
                        "slo_* gauges, edge-latched fast-burn pages / "
                        "slow-burn tickets via the obs_alert webhook "
                        "path (empty = built-in defaults when "
                        "--probe-every-s is set)")
    p.add_argument("--request-timeout-s", type=float,
                   default=d.request_timeout_s)
    p.add_argument("--emit-every-s", type=float, default=d.emit_every_s,
                   help="obs_router window record cadence")
    p.add_argument("--scale-up-queue-per-slot", type=float,
                   default=d.scale_up_queue_per_slot,
                   help="fleet queue depth per slot that arms "
                        "scale-up")
    p.add_argument("--scale-down-queue-per-slot", type=float,
                   default=d.scale_down_queue_per_slot,
                   help="fleet queue depth per slot below which "
                        "scale-down arms")
    p.add_argument("--scale-window-probes", type=int,
                   default=d.scale_window_probes,
                   help="consecutive probe rounds a scale condition "
                        "must hold (hysteresis)")
    p.add_argument("--scale-cooldown-s", type=float,
                   default=d.scale_cooldown_s,
                   help="hold after any scale action")
    p.add_argument("--min-replicas", type=int, default=d.min_replicas)
    p.add_argument("--max-replicas", type=int, default=d.max_replicas)
    p.add_argument("--ttft-slo-ms", type=float, default=d.ttft_slo_ms,
                   help="TTFT SLO in ms: worst-replica window p99 "
                        "above it counts as SLO burn and arms "
                        "scale-up (0 = off)")
    p.add_argument("--drain-grace-s", type=float, default=d.drain_grace_s,
                   help="SIGTERM -> graceful-drain budget before "
                        "SIGKILL on restart/stop")
    p.add_argument("--respawn-backoff-s", type=float,
                   default=d.respawn_backoff_s)
    p.add_argument("--run-id", default=d.run_id,
                   help="router identity on obs_router records "
                        "(default router-<host>-<pid>)")
    p.add_argument("--metrics-dir", default="",
                   help="directory for the router's metrics.jsonl + "
                        "flight recorder + per-replica logs/metrics")
    p.add_argument("--aot-cache", default="", metavar="DIR",
                   help="shared AOT program store forwarded to every "
                        "spawned replica (seconds-scale respawn/"
                        "scale-up cold start)")
    p.add_argument("--statsd", default="", metavar="HOST:PORT",
                   help="stream obs_router records as statsd gauges")
    p.add_argument("--obs-http", default="", metavar="URL",
                   help="POST obs_router records as line-JSON")
    p.add_argument("--obs-webhook", default="", metavar="URL",
                   help="POST one templated JSON payload per "
                        "obs_router EVENT record (evict/respawn/"
                        "scale; window records never page)")
    p.add_argument("serve_args", nargs=argparse.REMAINDER,
                   help="args after -- are passed to every spawned "
                        "'python -m tpunet.serve' child")
    return p


def build_router_config(args):
    from tpunet.config import RouterConfig
    return RouterConfig(
        host=args.host, port=args.port,
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        unhealthy_after=args.unhealthy_after,
        boot_timeout_s=args.boot_timeout_s,
        affinity_prefix=args.affinity_prefix,
        affinity_slack=args.affinity_slack,
        route_retries=args.route_retries,
        request_timeout_s=args.request_timeout_s,
        emit_every_s=args.emit_every_s,
        scale_up_queue_per_slot=args.scale_up_queue_per_slot,
        scale_down_queue_per_slot=args.scale_down_queue_per_slot,
        scale_window_probes=args.scale_window_probes,
        scale_cooldown_s=args.scale_cooldown_s,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        ttft_slo_ms=args.ttft_slo_ms,
        drain_grace_s=args.drain_grace_s,
        respawn_backoff_s=args.respawn_backoff_s,
        failover=args.failover,
        failover_journal_tokens=args.failover_journal_tokens,
        failover_retries=args.failover_retries,
        chaos=args.chaos,
        trace_sample=args.trace_sample,
        trace_all_on_error=args.trace_all_on_error,
        probe_every_s=args.probe_every_s,
        slo_policy=args.slo_policy,
        run_id=args.run_id)


def build_server(args):
    """Construct (but do not start) the RouterServer — shared by
    main() and tests."""
    from tpunet.obs.registry import JsonlSink, Registry
    from tpunet.router.core import Router
    from tpunet.router.frontend import RouterServer
    from tpunet.router.supervisor import Supervisor
    from tpunet.utils.logging import MetricsLogger

    cfg = build_router_config(args)
    if not args.replica and args.spawn < 1:
        print("python -m tpunet.router: error: nothing to route to — "
              "give --replica URL (repeatable) and/or --spawn N",
              file=sys.stderr, flush=True)
        raise SystemExit(2)
    if args.chaos:
        # A typo'd chaos spec is a loud exit-2 at router boot, not a
        # child-boot failure minutes later.
        from tpunet.serve.chaos import ServeChaosError, split_by_replica
        try:
            split_by_replica(args.chaos)
        except ServeChaosError as e:
            print(f"python -m tpunet.router: error: {e}",
                  file=sys.stderr, flush=True)
            raise SystemExit(2)
    if args.slo_policy:
        # A malformed SLO policy is a loud exit-2 at router boot, not
        # an unguarded fleet discovered mid-incident.
        from tpunet.obs.slo import SloPolicyError, load_policy
        try:
            load_policy(args.slo_policy)
        except (OSError, SloPolicyError) as e:
            print(f"python -m tpunet.router: error: --slo-policy: {e}",
                  file=sys.stderr, flush=True)
            raise SystemExit(2)
    serve_args = list(args.serve_args)
    if serve_args and serve_args[0] == "--":
        serve_args = serve_args[1:]
    supervisor = None
    if args.spawn > 0:
        supervisor = Supervisor(
            serve_args, directory=args.metrics_dir,
            drain_grace_s=cfg.drain_grace_s,
            aot_cache=args.aot_cache, chaos=args.chaos)
    registry = Registry()
    recorder = None
    metrics_logger = None
    exporters = []
    if args.metrics_dir:
        from tpunet.obs import flightrec
        recorder = flightrec.install(args.metrics_dir,
                                     run_id=args.run_id)
        metrics_logger = MetricsLogger(args.metrics_dir, resume=True)
        registry.add_sink(JsonlSink(metrics_logger))
    if args.statsd or args.obs_http or args.obs_webhook:
        from tpunet.config import ExportConfig
        from tpunet.obs.export import build_exporters
        exporters = build_exporters(
            ExportConfig(statsd=args.statsd, http=args.obs_http,
                         webhook=args.obs_webhook),
            registry)
        for exporter in exporters:
            registry.add_sink(exporter)
    router = Router(cfg, replica_urls=args.replica,
                    supervisor=supervisor, n_replicas=args.spawn,
                    registry=registry)
    return RouterServer(router, host=cfg.host, port=cfg.port,
                        metrics_logger=metrics_logger,
                        exporters=exporters, flight_recorder=recorder)


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    server = build_server(args)
    server.start()
    print(f"tpunet.router listening on "
          f"http://{args.host}:{server.port} "
          f"(replicas={len(server.router.replicas)}, "
          f"supervised={server.router.supervisor is not None})",
          flush=True)

    import threading
    stop = threading.Event()

    def _term(signum, frame):
        print(f"signal {signum}: draining router...", flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop.is_set():
        stop.wait(0.5)
        if not server.router.healthy:
            print(f"router control loop dead: {server.router.error}; "
                  "draining", file=sys.stderr, flush=True)
            stop.set()
    server.drain()
    print("router drained", flush=True)
    return 0 if server.router.error is None else 2


if __name__ == "__main__":
    sys.exit(main())
