"""Replica selection: least-loaded with session/prefix affinity.

Least-loaded is the workhorse: route to the replica with the lowest
``(queue_depth + active_slots) / slots`` from the live probes. On top
of it, AFFINITY keeps shared-prefix traffic together: requests that
carry the same ``"session"`` field — or whose first
``affinity_prefix`` prompt tokens/bytes match — hash to a stable
preferred replica via rendezvous (highest-random-weight) hashing, so
a conversation (or a fleet of requests sharing a long system prompt)
keeps hitting the replica whose KV pages for that prefix are warm
instead of re-prefilling on a cold one. Affinity yields to load: when
the preferred replica's load score exceeds the least-loaded one's by
more than ``affinity_slack``, least-loaded wins (a hot session must
not melt one replica while others idle).

Rendezvous hashing (rather than a modulo ring) means an evicted or
added replica only moves the keys that hashed to it — every other
session keeps its warm replica through membership changes.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional

from tpunet.router.replica import ReplicaHandle


def affinity_key(body: dict, prefix: int) -> Optional[str]:
    """The affinity hash key for one /v1/generate body, or None when
    the request has nothing to be affine on. An explicit ``session``
    wins; otherwise the first ``prefix`` prompt units (tokens or
    UTF-8 bytes) identify the shared prefix.

    Token-prefix requests hash the SAME digest the replicas' prefix
    KV cache keys its pages on (tpunet/serve/prefixcache/keys.py), so
    the digest the router routes by and the digest the cache hits on
    agree by construction: shared-prefix traffic lands where those
    exact pages are warm."""
    session = body.get("session")
    if session:
        return f"s:{session}"
    if prefix <= 0:
        return None
    tokens = body.get("tokens")
    if isinstance(tokens, list) and tokens:
        from tpunet.serve.prefixcache.keys import token_prefix_digest
        return "t:" + token_prefix_digest(tokens, prefix)
    prompt = body.get("prompt")
    if isinstance(prompt, str) and prompt:
        return "p:" + prompt.encode("utf-8")[:prefix].hex()
    return None


def _weight(key: str, name: str) -> int:
    """Deterministic rendezvous weight of (key, replica)."""
    digest = hashlib.sha256(f"{key}\x00{name}".encode()).digest()
    return int.from_bytes(digest[:8], "big")


def preferred_replica(replicas: List[ReplicaHandle],
                      key: str) -> Optional[ReplicaHandle]:
    """Highest-random-weight member for ``key`` among the given
    (already-filtered) replicas."""
    if not replicas:
        return None
    return max(replicas, key=lambda r: _weight(key, r.name))


def pick_replica(replicas: List[ReplicaHandle],
                 key: Optional[str] = None, *,
                 affinity_slack: float = 0.5,
                 exclude=()):
    """Pick the target replica for one request. Returns
    ``(replica, affinity_hit)`` — replica is None when nothing is
    routable (the frontend answers 503 + Retry-After), affinity_hit
    is True when the pick followed the affinity hash rather than pure
    least-loaded.

    ``exclude`` carries the replica names already tried by this
    request's re-route loop."""
    candidates = [r for r in replicas
                  if r.routable() and r.name not in exclude]
    if not candidates:
        return None, False
    least = min(candidates,
                key=lambda r: (r.load_score(), r.requests_routed,
                               r.name))
    if key is None:
        return least, False
    preferred = preferred_replica(candidates, key)
    if preferred is least:
        return least, True
    pref_load = preferred.load_score()
    if pref_load != float("inf") \
            and pref_load <= least.load_score() + affinity_slack:
        return preferred, True
    return least, False
