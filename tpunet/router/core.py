"""The Router: fleet state + the control loop that acts on it.

Everything the fleet's eyes already see — queue depth, slot
occupancy, TTFT windows, webhook pages — converges here and turns
into actions:

- **probe** every replica each round (``/healthz`` + ``/metrics``);
- **evict** replicas past their failure budget (or named by an
  AlertWebhook page: straggler / crash / thread_stalled) and, in
  supervisor mode, **respawn** them after a backoff — the respawned
  child boots through the AOT program store, so recovery is
  seconds-scale;
- **scale** the replica set on the hysteresis policy's decision
  (supervisor mode spawns/drains children; external mode emits the
  decision as advice);
- **emit** ``obs_router`` window records through the registry sinks
  (metrics.jsonl, exporters, the alert webhook for event records).

The router process never touches a device: probing, proxying, and
process supervision are stdlib work, so one router fronts any number
of accelerator-bound replicas without competing for their HBM.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from tpunet.obs import flightrec
from tpunet.obs.registry import Registry
from tpunet.obs.tracing import observe_trace
from tpunet.router import replica as rstate
from tpunet.router.balance import affinity_key, pick_replica
from tpunet.router.journal import RequestJournal
from tpunet.router.policy import SCALE_DOWN, SCALE_UP, AutoscalePolicy
from tpunet.router.records import (build_router_event,
                                   build_router_record)
from tpunet.router.replica import ReplicaHandle
from tpunet.router.supervisor import Supervisor

#: AlertWebhook page reasons the router treats as eviction triggers.
#: Everything else (loss spikes, gauge predicates...) is a trainer
#: concern and is acknowledged without action.
EVICT_REASONS = ("straggler", "crash", "thread_stalled")


class Router:
    """Replica set + control loop. The HTTP frontend
    (tpunet/router/frontend.py) proxies through ``pick`` /
    ``note_*``; ``python -m tpunet.router`` wires both."""

    def __init__(self, cfg, *, replica_urls: List[str] = (),
                 supervisor: Optional[Supervisor] = None,
                 n_replicas: int = 0, registry: Optional[Registry] = None,
                 clock=time.monotonic):
        self.cfg = cfg
        self.supervisor = supervisor
        self.registry = registry if registry is not None else Registry()
        if not self.registry.identity():
            import os
            import socket
            self.registry.set_identity(
                run_id=cfg.run_id
                or f"router-{socket.gethostname()}-{os.getpid()}",
                process_index=0, host=socket.gethostname())
        self._clock = clock
        self.policy = AutoscalePolicy(cfg, clock=clock)
        # SLO engine (tpunet/obs/slo.py): armed by --slo-policy and/or
        # the canary prober; None keeps the whole path zero-cost.
        self.slo = None
        if getattr(cfg, "slo_policy", "") \
                or getattr(cfg, "probe_every_s", 0.0) > 0:
            from tpunet.obs.slo import SloEngine, load_policy
            self.slo = SloEngine(
                load_policy(getattr(cfg, "slo_policy", "")),
                registry=self.registry, clock=clock)
        # Mid-stream failover journal (tpunet/router/journal.py):
        # owned here so the drain path can wait for in-flight
        # failovers instead of orphaning them with the frontend.
        self.journal = RequestJournal(
            getattr(cfg, "failover_journal_tokens", 4096))
        self.replicas: List[ReplicaHandle] = []
        self._boot_deadline: Dict[str, float] = {}
        self._respawn_at: Dict[str, float] = {}
        # Names of replicas whose PROCESS this router owns: only these
        # are killed/respawned on eviction — an external --replica URL
        # in a mixed fleet is taken out of rotation, never replaced by
        # a locally spawned child the operator didn't ask for.
        self._supervised: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._handle = None
        self._started = clock()
        self._last_emit = clock()
        self._next_index = 0
        self.error: Optional[str] = None
        for url in replica_urls:
            self._add_handle(url)
        if supervisor is not None:
            for _ in range(n_replicas):
                self._spawn_next()

    # -- replica set -----------------------------------------------------

    def _add_handle(self, url: str) -> ReplicaHandle:
        handle = ReplicaHandle(f"r{self._next_index}", url,
                               clock=self._clock)
        self._next_index += 1
        self._boot_deadline[handle.name] = (self._clock()
                                            + self.cfg.boot_timeout_s)
        self.replicas.append(handle)
        return handle

    def _spawn_next(self) -> ReplicaHandle:
        index = self._next_index
        proc = self.supervisor.spawn(index)
        handle = self._add_handle(
            f"http://{self.supervisor.host}:{proc.port}")
        self._supervised.add(handle.name)
        return handle

    def replicas_view(self) -> List[dict]:
        rows = [r.view() for r in list(self.replicas)]
        if self.supervisor is not None:
            for row in rows:
                proc = self.supervisor.get(int(row["name"][1:]))
                if proc is not None:
                    row["pid"] = proc.pid
                    row["alive"] = proc.alive()
        return rows

    def healthy_count(self) -> int:
        return sum(1 for r in self.replicas
                   if r.state == rstate.HEALTHY)

    # -- frontend surface ------------------------------------------------

    def pick(self, body: dict, exclude=()):
        """(replica, affinity_hit) for one request body (None when
        nothing routable)."""
        key = affinity_key(body, self.cfg.affinity_prefix)
        rep, hit = pick_replica(
            list(self.replicas), key,
            affinity_slack=self.cfg.affinity_slack, exclude=exclude)
        if rep is not None and hit:
            self.registry.counter("router_affinity_hits_total").inc()
        return rep, hit

    def note_routed(self, rep: ReplicaHandle) -> None:
        self.registry.counter("router_requests_total").inc()
        rep.note_routed()

    def note_rerouted(self, rep: ReplicaHandle) -> None:
        self.registry.counter("router_rerouted_total").inc()
        rep.note_failed()

    def note_rejected(self, *, synthetic: bool = False) -> None:
        """No routable replica. ``synthetic`` marks the prober's own
        traffic — the prober self-judges via ``note_probe`` (with its
        warmup gate), so the passive feed skips it here too."""
        self.registry.counter("router_rejected_total").inc()
        if self.slo is not None and not synthetic:
            self.slo.note_request(False)

    def note_failover(self, rep: ReplicaHandle, *,
                      tokens: int) -> None:
        """One mid-stream failover began: the stream's owner died (or
        wedged into eviction) after ``tokens`` tokens reached the
        client and a resume is being submitted to a survivor."""
        self.registry.counter("router_failovers_total").inc()
        flightrec.record("router",
                         f"failover from {rep.name} at {tokens} tok")
        self.registry.emit("obs_router", build_router_event(
            "failover", replica=rep.name, url=rep.url,
            cause="replica_failed_mid_stream",
            detail={"tokens_relayed": tokens}))

    def observe_e2e(self, seconds: float, *,
                    synthetic: bool = False) -> None:
        """One request finished end-to-end. ``synthetic`` marks the
        prober's own traffic — it judges itself client-side and feeds
        the SLO engine through ``note_probe``, so the passive feed
        skipping it keeps every probe counted exactly once."""
        self.registry.histogram("router_e2e_s").observe(seconds)
        if self.slo is not None and not synthetic:
            self.slo.note_request(True)
            self.slo.note_latency("e2e", seconds)

    def note_trace(self, record: dict) -> None:
        """One router-hop ``obs_trace`` span closed (sampled request
        finished, or an unsampled one earned tail capture via
        trace-all-on-error): bump the ``trace_*`` instruments and ship
        the record through the sinks."""
        observe_trace(self.registry, record)
        self.registry.emit("obs_trace", record)

    def replica_failed(self, rep: ReplicaHandle) -> None:
        """A proxied request hit a transport failure: probe it NOW
        (off the probe cadence) so a dead replica leaves the routable
        set within one request, not one probe interval. Same guards
        as the control loop: boot grace protects a respawning child
        from a stale in-flight failure, and an already-evicted
        replica is not evicted again."""
        if rep.state in (rstate.DEAD, rstate.EVICTED):
            return
        if not rep.probe(self.cfg.probe_timeout_s):
            in_boot = (rep.state == rstate.STARTING
                       and self._clock() < self._boot_deadline.get(
                           rep.name, 0.0))
            if not in_boot \
                    and rep.fail_streak >= self.cfg.unhealthy_after:
                self._evict(rep, cause="probe_failures")

    # -- webhook consumption ---------------------------------------------

    def on_page(self, payload: dict) -> bool:
        """Consume one AlertWebhook POST (the documented wire format:
        kind/reason/run_id/detail). A straggler / crash /
        thread_stalled page naming a replica's run_id evicts it;
        anything else is acknowledged without action. Returns True
        when an eviction was triggered."""
        reason = str(payload.get("reason") or "")
        kind = str(payload.get("kind") or "")
        if reason not in EVICT_REASONS and kind != "obs_crash":
            return False
        run_id = str(payload.get("run_id")
                     or payload.get("stream") or "")
        if not run_id:
            return False
        # Fleet-aggregator pages key streams as "run_id/process_index";
        # replicas are single-process, so strip that suffix and match
        # EXACTLY (a prefix match would evict router-replica-1 on a
        # page for router-replica-10).
        run_id = run_id.split("/", 1)[0]
        target = None
        for rep in list(self.replicas):
            if rep.run_id and run_id == rep.run_id:
                target = rep
                break
        if target is None or target.state in (rstate.DEAD,
                                              rstate.EVICTED):
            return False
        self._evict(target, cause=f"webhook:{reason or kind}",
                    detail=payload)
        return True

    # -- control loop ----------------------------------------------------

    def start(self) -> "Router":
        self._handle = flightrec.register_thread("router-control",
                                                 stall_after_s=120.0)
        flightrec.record("router",
                         f"control start replicas={len(self.replicas)}")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpunet-router-control")
        self._thread.start()
        return self

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                self._handle.beat("busy")
                self._round()
                self._handle.beat("idle")
                self._stop.wait(self.cfg.probe_interval_s)
        except BaseException as e:  # noqa: BLE001 — control-loop death
            # flips the router's /healthz; the frontend keeps proxying
            # on the last-known replica states.
            self.error = f"{type(e).__name__}: {e}"
            flightrec.record("router", f"control error: {e}")

    def _round(self) -> None:
        """One control round: probe -> evict -> respawn -> scale ->
        emit."""
        reg = self.registry
        now = self._clock()
        for rep in list(self.replicas):
            t0 = time.perf_counter()
            ok = rep.probe(self.cfg.probe_timeout_s)
            reg.histogram("router_probe_s").observe(
                time.perf_counter() - t0)
            if not ok:
                reg.counter("router_probe_failures_total").inc()
                in_boot = (rep.state == rstate.STARTING
                           and now < self._boot_deadline.get(
                               rep.name, 0.0))
                if not in_boot \
                        and rep.fail_streak >= self.cfg.unhealthy_after \
                        and rep.state not in (rstate.DEAD,
                                              rstate.EVICTED):
                    self._evict(rep, cause="probe_failures")
        self._respawn_due(now)
        self._autoscale()
        self._export_gauges()
        if self.slo is not None:
            # Every round, not just on emit cadence: burn-rate pages
            # must fire at probe-loop latency (the record bodies are
            # discarded here; emit_record re-evaluates on its cadence).
            self.slo.evaluate()
        if self.cfg.emit_every_s > 0 \
                and now - self._last_emit >= self.cfg.emit_every_s:
            self.emit_record()

    def _evict(self, rep: ReplicaHandle, *, cause: str,
               detail: Optional[dict] = None) -> None:
        """Take a replica out of rotation (and, when this router owns
        its process, kill it and schedule the respawn). Idempotent:
        concurrent failure reports evict once."""
        with self._lock:
            if rep.state in (rstate.DEAD, rstate.EVICTED):
                return
            rep.mark(rstate.EVICTED if cause.startswith("webhook")
                     else rstate.DEAD)
        self.registry.counter("router_evictions_total").inc()
        flightrec.record("router", f"evict {rep.name} {cause}")
        self.registry.emit("obs_router", build_router_event(
            "evict", replica=rep.name, url=rep.url, cause=cause,
            detail=detail))
        if self.supervisor is not None \
                and rep.name in self._supervised:
            index = int(rep.name[1:])
            self.supervisor.kill(index)
            self._respawn_at[rep.name] = (self._clock()
                                          + self.cfg.respawn_backoff_s)

    def _respawn_due(self, now: float) -> None:
        if self.supervisor is None:
            return
        for rep in list(self.replicas):
            due = self._respawn_at.get(rep.name)
            if due is None or now < due:
                continue
            del self._respawn_at[rep.name]
            index = int(rep.name[1:])
            proc = self.supervisor.respawn(index)
            rep.reset_for_respawn(
                f"http://{self.supervisor.host}:{proc.port}")
            self._boot_deadline[rep.name] = (self._clock()
                                             + self.cfg.boot_timeout_s)
            self.registry.counter("router_respawns_total").inc()
            flightrec.record("router",
                             f"respawn {rep.name} port={proc.port}")
            self.registry.emit("obs_router", build_router_event(
                "respawn", replica=rep.name, url=rep.url,
                cause="evicted"))

    def _fleet_ttft_p99(self) -> Optional[float]:
        """Worst healthy replica's window TTFT p99 from the probes —
        a scale SIGNAL, deliberately not a merged fleet percentile
        (the aggregator owns the honest merge; the policy only needs
        'someone is burning the SLO')."""
        vals = [r.ttft_p99_s for r in self.replicas
                if r.state == rstate.HEALTHY
                and r.ttft_p99_s is not None]
        return max(vals) if vals else None

    def _autoscale(self) -> None:
        live = [r for r in self.replicas
                if r.state in (rstate.HEALTHY, rstate.STARTING,
                               rstate.DRAINING)]
        healthy = [r for r in live if r.state == rstate.HEALTHY]
        queue_depth = sum(r.queue_depth for r in healthy)
        slots = sum(r.slots for r in healthy)
        decision = self.policy.observe(
            queue_depth=queue_depth, slots=slots,
            ttft_p99_s=self._fleet_ttft_p99(), replicas=len(live))
        if decision is None:
            return
        old = len(live)
        if decision == SCALE_UP:
            self.registry.counter("router_scale_ups_total").inc()
            if self.supervisor is not None:
                handle = self._spawn_next()
                flightrec.record("router", f"scale_up {handle.name}")
            self.registry.emit("obs_router", build_router_event(
                SCALE_UP, cause="policy", old_replicas=old,
                new_replicas=old + 1))
        elif decision == SCALE_DOWN:
            victim = min(healthy, default=None,
                         key=lambda r: (r.load_score(), r.name))
            if victim is None:
                return         # nothing drainable this round
            self.registry.counter("router_scale_downs_total").inc()
            victim.mark(rstate.DRAINING)
            if self.supervisor is not None:
                self._drain_remove_async(victim)
            flightrec.record("router", f"scale_down {victim.name}")
            self.registry.emit("obs_router", build_router_event(
                SCALE_DOWN, replica=victim.name, cause="policy",
                old_replicas=old, new_replicas=max(0, old - 1)))

    def _drain_remove_async(self, rep: ReplicaHandle) -> None:
        """Drain-stop a scale-down victim off the control loop (the
        graceful drain can take drain_grace_s; probing must not
        stall behind it)."""
        index = int(rep.name[1:])

        def work() -> None:
            handle = flightrec.register_thread(
                f"router-drain-{rep.name}", stall_after_s=0.0)
            handle.beat("busy")
            self.supervisor.remove(index)
            with self._lock:
                if rep in self.replicas:
                    self.replicas.remove(rep)
                self._supervised.discard(rep.name)
            self._boot_deadline.pop(rep.name, None)
            handle.beat("idle")

        threading.Thread(target=work, daemon=True,
                         name=f"tpunet-router-drain-{rep.name}").start()

    # -- obs -------------------------------------------------------------

    def _export_gauges(self) -> None:
        reg = self.registry
        healthy = [r for r in self.replicas
                   if r.state == rstate.HEALTHY]
        reg.gauge("router_replicas").set(len(self.replicas))
        reg.gauge("router_replicas_healthy").set(len(healthy))
        reg.gauge("router_fleet_queue_depth").set(
            sum(r.queue_depth for r in healthy))
        reg.gauge("router_fleet_active_slots").set(
            sum(r.active_slots for r in healthy))
        reg.gauge("router_fleet_slots").set(
            sum(r.slots for r in healthy))
        burn = self.policy.slo_burn(self._fleet_ttft_p99())
        if burn is not None:
            reg.gauge("router_ttft_slo_burn").set(round(burn, 4))

    def emit_record(self, final: bool = False) -> None:
        now = self._clock()
        window = now - self._last_emit
        self._last_emit = now
        record = build_router_record(
            self.registry, replicas=self.replicas_view(),
            uptime_s=now - self._started, window_s=window,
            scale_decision=self.policy.last_decision,
            ttft_slo_burn=self.policy.slo_burn(self._fleet_ttft_p99()),
            final=final)
        from tpunet.obs.flightrec.threads import THREADS
        THREADS.export_gauges(self.registry)
        self.registry.emit("obs_router", record)
        if self.slo is not None:
            for slo_record in self.slo.evaluate():
                self.registry.emit("obs_slo", slo_record)
        self.registry.reset_window()

    # -- lifecycle -------------------------------------------------------

    @property
    def healthy(self) -> bool:
        return (self.error is None and self._thread is not None
                and self._thread.is_alive())

    def drain(self) -> None:
        """Stop the control loop, wait out in-flight failovers, flush
        the final record, drain every supervised child. The failover
        wait and the children's graceful drain share ONE grace budget
        (``drain_grace_s``): a journaled request mid-failover is not
        orphaned, and a resumed stream is back in a replica's
        in-flight set where the child's own drain finishes it."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        deadline = time.monotonic() + self.cfg.drain_grace_s
        while self.journal.active_failovers() \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        self.emit_record(final=True)
        if self.supervisor is not None:
            self.supervisor.stop_all(
                drain=True,
                grace_s=max(0.0, deadline - time.monotonic()))
