"""Stdlib threaded HTTP proxy: one endpoint in front of N replicas.

Endpoints:

- ``POST /v1/generate`` — picked by affinity + least-loaded and
  proxied to a replica (streaming ndjson relayed chunk-by-chunk).
  A replica that fails or answers 503/429 BEFORE any response byte
  reached the client is retried against another replica (up to
  ``route_retries`` re-routes); client errors (400/413) relay
  immediately — re-routing a bad request just fails it N times.
- ``POST /v1/classify`` — same proxy, no affinity (stateless).
- ``POST /webhook`` — AlertWebhook receiver: straggler / crash /
  thread_stalled pages naming a replica's run_id evict it
  (``--obs-webhook http://router:PORT/webhook`` on any fleet
  dashboard or serve CLI closes the loop).
- ``GET /healthz`` — router liveness + routable-replica count (503
  only when the control loop died).
- ``GET /metrics`` — the router registry snapshot (``router_*``).
- ``GET /replicas`` — per-replica state/load/counters (the e2e tests
  and ``bench_serve --router`` read replica request counts here).

With no routable replica the router answers 503 with ``Retry-After:
1`` — the same backpressure contract the replicas themselves speak.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpunet.obs import flightrec
from tpunet.router.core import Router
from tpunet.serve import httpjson


class RouterServer:
    """Owns the Router and the HTTP listener (``port=0`` binds an
    ephemeral port for tests)."""

    def __init__(self, router: Router, *, host: str = "127.0.0.1",
                 port: int = 8100, metrics_logger=None, exporters=(),
                 flight_recorder=None):
        self.router = router
        self.registry = router.registry
        self._metrics_logger = metrics_logger
        self._exporters = list(exporters)
        self._flightrec = flight_recorder
        self._drained = False
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._serve_thread: Optional[threading.Thread] = None

    def start(self) -> "RouterServer":
        self.router.start()
        # Inventory-only (stall budget 0), like the serve listener:
        # serve_forever blocks in accept() and cannot beat.
        flightrec.register_thread("router-http")
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="tpunet-router-http")
        self._serve_thread.start()
        return self

    def drain(self) -> None:
        """Stop listening, stop the control loop, drain supervised
        children, flush sinks. Idempotent."""
        if self._drained:
            return
        self._drained = True
        flightrec.record("router", "frontend drain")
        self.httpd.shutdown()
        self.httpd.server_close()
        self.router.drain()
        for exporter in self._exporters:
            try:
                exporter.close()
            except Exception:  # noqa: BLE001 — a dead endpoint must
                pass           # not block shutdown
        if self._flightrec is not None:
            flightrec.close(self._flightrec)
            self._flightrec = None

    close = drain


def _make_handler(server: RouterServer):
    router = server.router
    cfg = router.cfg

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102 — metrics
            pass                            # carry the signal

        # -- helpers ---------------------------------------------------

        def _json(self, code: int, obj: dict, headers=()) -> None:
            httpjson.write_json(self, code, obj, headers)

        def _read_body(self) -> dict:
            return httpjson.read_json_body(self)

        # -- GET -------------------------------------------------------

        def do_GET(self):  # noqa: N802 (stdlib handler API)
            if self.path == "/healthz":
                routable = sum(1 for r in router.replicas
                               if r.routable())
                if not router.healthy:
                    self._json(503, {
                        "status": "unhealthy",
                        "error": router.error or "control loop dead"})
                else:
                    self._json(200, {
                        "status": "ok" if routable else "no_replicas",
                        "replicas": len(router.replicas),
                        "routable": routable})
                return
            if self.path == "/metrics":
                self._json(200, server.registry.snapshot())
                return
            if self.path == "/replicas":
                self._json(200, {"replicas": router.replicas_view()})
                return
            self._json(404, {"error": "not found"})

        # -- POST ------------------------------------------------------

        def do_POST(self):  # noqa: N802
            try:
                body = self._read_body()
            except ValueError as e:
                self._json(400, {"error": str(e)})
                return
            if self.path == "/v1/generate":
                self._proxy(body, "/v1/generate",
                            stream=bool(body.get("stream")),
                            affine=True)
            elif self.path == "/v1/classify":
                self._proxy(body, "/v1/classify", stream=False,
                            affine=False)
            elif self.path == "/webhook":
                accepted = router.on_page(body)
                self._json(200, {"accepted": accepted})
            else:
                self._json(404, {"error": "not found"})

        # -- proxying --------------------------------------------------

        def _proxy(self, body: dict, path: str, *, stream: bool,
                   affine: bool) -> None:
            raw = json.dumps(body).encode()
            t0 = time.perf_counter()
            tried = set()
            last_error = None
            for _ in range(cfg.route_retries + 1):
                rep, _hit = (router.pick(body, exclude=tried) if affine
                             else router.pick({}, exclude=tried))
                if rep is None:
                    break
                req = urllib.request.Request(
                    rep.url + path, raw,
                    {"Content-Type": "application/json"})
                try:
                    resp = urllib.request.urlopen(
                        req, timeout=cfg.request_timeout_s)
                except urllib.error.HTTPError as e:
                    if e.code in (503, 429):
                        # Draining / overloaded: honor Retry-After,
                        # re-route to another replica.
                        retry_after = float(
                            e.headers.get("Retry-After") or 0)
                        if retry_after > 0:
                            rep.backoff(retry_after)
                        e.read()
                        e.close()
                        tried.add(rep.name)
                        router.note_rerouted(rep)
                        last_error = (e.code, {"error": "replica_busy",
                                               "replica": rep.name})
                        continue
                    # Client/server error from a live replica: relay
                    # verbatim (re-routing a 400 fails it N times).
                    router.note_routed(rep)
                    try:
                        payload = json.loads(e.read())
                    except Exception:  # noqa: BLE001
                        payload = {"error": f"replica returned {e.code}"}
                    e.close()
                    self._json(e.code, payload)
                    return
                except Exception:  # noqa: BLE001 — connection refused/
                    # reset/timeout: the replica is gone; probe it off-
                    # cadence and try another.
                    tried.add(rep.name)
                    router.note_rerouted(rep)
                    router.replica_failed(rep)
                    last_error = (502, {"error": "replica_unreachable",
                                        "replica": rep.name})
                    continue
                router.note_routed(rep)
                try:
                    if stream:
                        self._relay_stream(resp)
                    else:
                        self._relay_json(resp)
                finally:
                    resp.close()
                    router.observe_e2e(time.perf_counter() - t0)
                return
            router.note_rejected()
            code, payload = last_error or (
                503, {"error": "no_replicas",
                      "detail": "no routable replica"})
            self._json(code, payload,
                       headers=(("Retry-After", "1"),))

        def _relay_json(self, resp) -> None:
            payload = resp.read()
            self.send_response(resp.status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _relay_stream(self, resp) -> None:
            """Relay replica ndjson chunk-by-chunk (urllib de-chunks
            the replica side; we re-chunk toward the client). A
            replica death mid-stream ends the stream with an error
            done-frame — tokens already forwarded cannot be unsent,
            so mid-stream failover is a non-goal; the client retries
            and lands on a live replica."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(data: bytes) -> None:
                self.wfile.write(f"{len(data):x}\r\n".encode()
                                 + data + b"\r\n")
                self.wfile.flush()

            try:
                for line in resp:
                    chunk(line)
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                raise
            except OSError:
                # Replica-side failure mid-relay: close the stream
                # honestly (the flight recorder notes it; the done
                # frame says error, not length).
                flightrec.record("router", "stream relay broke")
                try:
                    chunk(json.dumps(
                        {"done": True, "finish_reason": "error",
                         "error": "replica failed mid-stream"})
                        .encode() + b"\n")
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

    return Handler
