"""Stdlib threaded HTTP proxy: one endpoint in front of N replicas.

Endpoints:

- ``POST /v1/generate`` — picked by affinity + least-loaded and
  proxied to a replica (streaming ndjson relayed chunk-by-chunk).
  A replica that fails or answers 503/429 BEFORE any response byte
  reached the client is retried against another replica (up to
  ``route_retries`` re-routes); client errors (400/413) relay
  immediately — re-routing a bad request just fails it N times.
  Streams get MID-STREAM FAILOVER (``--failover``, default on): the
  frontend journals every relayed token, and a replica that dies
  after first bytes reached the client is replaced — the request is
  re-submitted to a survivor with ``resume_tokens`` and the client's
  stream continues with no error frame (greedy: token-identical to an
  uninterrupted run; sampled: deterministic per (seed, step) — see
  docs/serving.md "Mid-stream failover & serve-tier chaos").
- ``POST /v1/classify`` — same proxy, no affinity (stateless).
- ``POST /webhook`` — AlertWebhook receiver: straggler / crash /
  thread_stalled pages naming a replica's run_id evict it
  (``--obs-webhook http://router:PORT/webhook`` on any fleet
  dashboard or serve CLI closes the loop).
- ``GET /healthz`` — router liveness + routable-replica count (503
  only when the control loop died).
- ``GET /metrics`` — the router registry snapshot (``router_*``).
- ``GET /replicas`` — per-replica state/load/counters (the e2e tests
  and ``bench_serve --router`` read replica request counts here).

Client deadline propagation: an ``X-Deadline-Ms`` request header is
honored end-to-end — every hop (including failover retries) forwards
the REMAINING budget to the replica, and expiry before success is a
504 carrying the partial token count (mid-stream: a ``deadline`` done
frame).

With no routable replica the router answers 503 with ``Retry-After:
1`` — the same backpressure contract the replicas themselves speak.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import types
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpunet.obs import flightrec, tracing
from tpunet.router import replica as rstate
from tpunet.router.core import Router
from tpunet.serve import httpjson

#: Relay poll period while a stream is quiet: bounds how long a
#: wedged-but-connected replica can hold a stream before the relay
#: notices its eviction (the stall-evict -> failover path).
_STREAM_POLL_S = 0.5


class _StreamReader:
    """Line reader for one upstream response on its own thread.

    http.client response objects cannot be poll-read: a socket
    timeout permanently poisons them ("cannot read from timed out
    object"), so the relay blocks a dedicated reader in
    ``readline()`` and polls its queue instead — replica-eviction and
    deadline checks run between polls, and abandoning a wedged stream
    is just closing the response (the blocked read unblocks and the
    thread exits)."""

    _registered = False

    def __init__(self, resp):
        import queue
        self._resp = resp
        self._q: "queue.Queue" = queue.Queue()
        self._empty = queue.Empty
        # One inventory-only registration (stall budget 0) for the
        # whole relay-reader population, like the router-http
        # listener: readers legitimately block in readline for a
        # stream's lifetime, and per-stream handles would leave a
        # stale never-beating entry per request in the process-global
        # registry.
        if not _StreamReader._registered:
            _StreamReader._registered = True
            flightrec.register_thread("router-relay")
        self._thread = threading.Thread(
            target=self._run, args=(resp,), daemon=True,
            name="tpunet-router-relay")
        self._thread.start()

    def _run(self, resp) -> None:
        try:
            while True:
                line = resp.readline()
                self._q.put(("line", line))
                if not line:
                    return
        except Exception as e:  # noqa: BLE001 — any read failure is
            # the same relay signal: the stream is over.
            self._q.put(("exc", e))

    def get(self, timeout: float):
        """("line", bytes) / ("exc", exception) / None on poll
        timeout. A b"" line is upstream EOF."""
        try:
            return self._q.get(timeout=timeout)
        except self._empty:
            return None

    def close(self) -> None:
        """Tear the stream down even when the reader is still blocked
        mid-readline (a wedged replica): ``resp.close()`` alone would
        deadlock on the buffered reader's lock, so the SOCKET is shut
        down first — the blocked recv returns EOF, the thread exits,
        and only then is the response closed. A reader that still
        won't die keeps its response leaked (daemon thread) rather
        than deadlocking the relay."""
        import socket
        sock = getattr(getattr(self._resp, "fp", None), "raw", None)
        sock = getattr(sock, "_sock", None)
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._thread.join(timeout=2.0)
        if self._thread.is_alive():
            return
        try:
            self._resp.close()
        except Exception:  # noqa: BLE001
            pass


class RouterServer:
    """Owns the Router and the HTTP listener (``port=0`` binds an
    ephemeral port for tests)."""

    def __init__(self, router: Router, *, host: str = "127.0.0.1",
                 port: int = 8100, metrics_logger=None, exporters=(),
                 flight_recorder=None):
        self.router = router
        self.registry = router.registry
        self._metrics_logger = metrics_logger
        self._exporters = list(exporters)
        self._flightrec = flight_recorder
        self._drained = False
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._serve_thread: Optional[threading.Thread] = None
        self.prober = None

    def start(self) -> "RouterServer":
        self.router.start()
        # Inventory-only (stall budget 0), like the serve listener:
        # serve_forever blocks in accept() and cannot beat.
        flightrec.register_thread("router-http")
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="tpunet-router-http")
        self._serve_thread.start()
        cfg = self.router.cfg
        if getattr(cfg, "probe_every_s", 0.0) > 0 \
                and self.router.slo is not None:
            # The canary probes the router's PUBLIC endpoint — the
            # full proxy path — via loopback (the prober lives in the
            # router process; a wildcard bind still answers there).
            from tpunet.router.prober import Prober
            host = cfg.host
            if host in ("", "0.0.0.0", "::"):
                host = "127.0.0.1"
            self.prober = Prober(
                cfg, self.router.slo, registry=self.registry,
                base_url=f"http://{host}:{self.port}").start()
        return self

    def drain(self) -> None:
        """Stop listening, stop the control loop, drain supervised
        children, flush sinks. Idempotent. In-flight failovers are
        waited for inside ``Router.drain`` against the shared grace
        budget — the journal is never orphaned with its frontend
        thread."""
        if self._drained:
            return
        self._drained = True
        flightrec.record("router", "frontend drain")
        if self.prober is not None:
            self.prober.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.router.drain()
        for exporter in self._exporters:
            try:
                exporter.close()
            except Exception:  # noqa: BLE001 — a dead endpoint must
                pass           # not block shutdown
        if self._flightrec is not None:
            flightrec.close(self._flightrec)
            self._flightrec = None

    close = drain


def _make_handler(server: RouterServer):
    router = server.router
    cfg = router.cfg

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: D102 — metrics
            pass                            # carry the signal

        # -- helpers ---------------------------------------------------

        def _json(self, code: int, obj: dict, headers=()) -> None:
            httpjson.write_json(self, code, obj, headers)

        def _read_body(self) -> dict:
            return httpjson.read_json_body(self)

        def _client_deadline(self) -> Optional[float]:
            """Absolute monotonic deadline from the client's
            ``X-Deadline-Ms`` header (None when absent; raises
            ValueError on garbage)."""
            hdr = self.headers.get("X-Deadline-Ms")
            if hdr is None:
                return None
            ms = float(hdr)               # ValueError -> 400
            if ms <= 0:
                raise ValueError(
                    f"X-Deadline-Ms must be positive, got {hdr!r}")
            return time.monotonic() + ms / 1e3

        def _trace_context(self):
            """(trace_id, sampled) for this request (tpunet/obs/
            tracing.py): a client-supplied valid ``X-Trace-Id`` is
            adopted and always sampled (explicit opt-in); otherwise a
            fresh id is minted and head-sampled at
            ``cfg.trace_sample``. ("", False) when tracing is fully
            off — call sites short-circuit on the empty id."""
            tid = self.headers.get(tracing.TRACE_HEADER)
            if tracing.valid_trace_id(tid):
                return tid, True
            if cfg.trace_sample <= 0 and not cfg.trace_all_on_error:
                return "", False
            tid = tracing.mint_trace_id()
            return tid, tracing.should_sample(cfg.trace_sample, tid)

        @staticmethod
        def _replica_headers(deadline_t: Optional[float],
                             trace=None) -> dict:
            """Headers for one replica-bound request: the remaining
            deadline budget rides along so the engine's scheduler
            enforces the CLIENT's clock, and a failover retry can
            never exceed the original budget. A sampled trace context
            (``trace``: anything with trace_id/trace_sampled/hop —
            a JournalEntry or the _proxy shim) stamps the trace
            headers on the hop, failover re-submits included."""
            headers = {"Content-Type": "application/json"}
            if deadline_t is not None:
                remaining = max(1.0,
                                1e3 * (deadline_t - time.monotonic()))
                headers["X-Deadline-Ms"] = f"{remaining:.0f}"
            if trace is not None and trace.trace_sampled:
                headers[tracing.TRACE_HEADER] = trace.trace_id
                headers[tracing.SAMPLED_HEADER] = "1"
                headers[tracing.HOP_HEADER] = str(trace.hop)
            return headers

        # -- GET -------------------------------------------------------

        def do_GET(self):  # noqa: N802 (stdlib handler API)
            if self.path == "/healthz":
                routable = sum(1 for r in router.replicas
                               if r.routable())
                if not router.healthy:
                    self._json(503, {
                        "status": "unhealthy",
                        "error": router.error or "control loop dead"})
                else:
                    self._json(200, {
                        "status": "ok" if routable else "no_replicas",
                        "replicas": len(router.replicas),
                        "routable": routable})
                return
            if self.path == "/metrics":
                self._json(200, server.registry.snapshot())
                return
            if self.path == "/replicas":
                self._json(200, {"replicas": router.replicas_view()})
                return
            self._json(404, {"error": "not found"})

        # -- POST ------------------------------------------------------

        def do_POST(self):  # noqa: N802
            try:
                body = self._read_body()
            except ValueError as e:
                self._json(400, {"error": str(e)})
                return
            if self.path == "/v1/generate":
                if body.get("stream") and cfg.failover:
                    self._generate_stream(body)
                else:
                    self._proxy(body, "/v1/generate",
                                stream=bool(body.get("stream")),
                                affine=True)
            elif self.path == "/v1/classify":
                self._proxy(body, "/v1/classify", stream=False,
                            affine=False)
            elif self.path == "/webhook":
                accepted = router.on_page(body)
                self._json(200, {"accepted": accepted})
            else:
                self._json(404, {"error": "not found"})

        # -- replica connection (pre-first-byte retry loop) ------------

        def _open_on_fleet(self, body: dict, path: str, tried: set,
                           *, affine: bool,
                           deadline_t: Optional[float], trace=None):
            """Pick a replica and open the request, re-routing around
            dead/draining replicas BEFORE any response byte exists.
            Returns one of::

                ("resp", resp, rep)        connection open, routed
                ("relay", code, payload)   live replica's own error —
                                           relay verbatim
                ("reject", code, payload, headers)
                                           exhausted / expired

            Every OPEN attempt is one trace hop: ``trace.hop``
            increments per attempt (route retries and failover
            re-submits alike), so the headers a replica sees name the
            span its breadcrumbs belong to.
            """
            raw = json.dumps(body).encode()
            last_error = None
            for _ in range(cfg.route_retries + 1):
                if deadline_t is not None \
                        and time.monotonic() >= deadline_t:
                    return ("reject", 504,
                            {"error": "deadline", "n_tokens": 0}, ())
                rep, _hit = (router.pick(body, exclude=tried) if affine
                             else router.pick({}, exclude=tried))
                if rep is None:
                    break
                if trace is not None and trace.trace_sampled:
                    trace.hop += 1
                req = urllib.request.Request(
                    rep.url + path, raw,
                    self._replica_headers(deadline_t, trace))
                try:
                    resp = urllib.request.urlopen(
                        req, timeout=cfg.request_timeout_s)
                except urllib.error.HTTPError as e:
                    if e.code in (503, 429):
                        # Draining / overloaded: honor Retry-After,
                        # re-route to another replica.
                        retry_after = float(
                            e.headers.get("Retry-After") or 0)
                        if retry_after > 0:
                            rep.backoff(retry_after)
                        e.read()
                        e.close()
                        tried.add(rep.name)
                        router.note_rerouted(rep)
                        last_error = (e.code,
                                      {"error": "replica_busy",
                                       "replica": rep.name})
                        continue
                    # Client/server error from a live replica: relay
                    # verbatim (re-routing a 400 fails it N times).
                    router.note_routed(rep)
                    try:
                        payload = json.loads(e.read())
                    except Exception:  # noqa: BLE001
                        payload = {"error":
                                   f"replica returned {e.code}"}
                    e.close()
                    return ("relay", e.code, payload)
                except Exception:  # noqa: BLE001 — connection refused/
                    # reset/timeout: the replica is gone; probe it off-
                    # cadence and try another.
                    tried.add(rep.name)
                    router.note_rerouted(rep)
                    router.replica_failed(rep)
                    last_error = (502, {"error": "replica_unreachable",
                                        "replica": rep.name})
                    continue
                router.note_routed(rep)
                if trace is not None and trace.trace_sampled:
                    tracing.crumb("open", trace.trace_id, trace.hop,
                                  rep=rep.name)
                return ("resp", resp, rep)
            router.note_rejected(synthetic=bool(body.get("probe")))
            code, payload = last_error or (
                503, {"error": "no_replicas",
                      "detail": "no routable replica"})
            return ("reject", code, payload, (("Retry-After", "1"),))

        # -- non-stream proxying ---------------------------------------

        def _proxy(self, body: dict, path: str, *, stream: bool,
                   affine: bool) -> None:
            t0 = time.perf_counter()
            try:
                deadline_t = self._client_deadline()
            except ValueError as e:
                self._json(400, {"error": str(e)})
                return
            # Non-journal paths still propagate trace context so the
            # replica's span exists; the router-hop record is a
            # stream-path concern (the relay owns the e2e story).
            tid, sampled = self._trace_context()
            trace = (types.SimpleNamespace(
                trace_id=tid, trace_sampled=sampled, hop=0)
                if tid else None)
            synthetic = bool(body.get("probe"))
            tried: set = set()
            while True:
                opened = self._open_on_fleet(body, path, tried,
                                             affine=affine,
                                             deadline_t=deadline_t,
                                             trace=trace)
                if opened[0] == "relay":
                    _, code, payload = opened
                    self._json(code, payload)
                    return
                if opened[0] == "reject":
                    _, code, payload, headers = opened
                    self._json(code, payload, headers=headers)
                    return
                _, resp, rep = opened
                if stream:
                    # Legacy (--no-failover) stream relay: a replica
                    # death mid-stream ends the stream with an honest
                    # error frame and the client retries.
                    try:
                        self._relay_stream(resp)
                    finally:
                        resp.close()
                        router.observe_e2e(time.perf_counter() - t0,
                                           synthetic=synthetic)
                    return
                # Non-stream: buffer the WHOLE body before the first
                # client byte — a replica death mid-read is then fully
                # retryable on another replica (nothing was sent, and
                # generation is deterministic per (seed, step)).
                try:
                    payload = resp.read()
                    status = resp.status
                except (OSError, http.client.HTTPException):
                    resp.close()
                    tried.add(rep.name)
                    router.note_rerouted(rep)
                    router.replica_failed(rep)
                    continue
                resp.close()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                router.observe_e2e(time.perf_counter() - t0,
                                   synthetic=synthetic)
                return

        # -- streaming with mid-stream failover ------------------------

        def _chunk(self, data: bytes) -> None:
            self.wfile.write(f"{len(data):x}\r\n".encode()
                             + data + b"\r\n")
            self.wfile.flush()

        def _finish_frame(self, entry, reason: str,
                          error: Optional[str] = None) -> None:
            """Terminate the client stream with a router-authored done
            frame (degradation paths: journal cap, retries exhausted,
            deadline). Client disconnects are swallowed — there is
            nobody left to tell."""
            frame = {"done": True, "finish_reason": reason,
                     "n_tokens": len(entry.tokens)}
            if entry.failover_count:
                frame["failover_count"] = entry.failover_count
            if error:
                frame["error"] = error
            try:
                self._chunk((json.dumps(frame) + "\n").encode())
                self.wfile.write(b"0\r\n\r\n")
            except OSError:
                pass

        def _close_trace(self, entry, reason: str, t0: float,
                         error: str = "") -> None:
            """Close the router-hop trace span: a ``finish`` crumb
            plus one router-role ``obs_trace`` record — for every
            sampled request, and (trace-all-on-error tail capture) for
            any UNsampled request that failed over or errored. The
            empty-trace_id check is the whole cost on the untraced
            path."""
            if not entry.trace_id:
                return
            interesting = bool(entry.failover_count or error
                               or reason == "error")
            if not (entry.trace_sampled
                    or (cfg.trace_all_on_error and interesting)):
                return
            if entry.trace_sampled:
                tracing.crumb("finish", entry.trace_id, 0,
                              reason=reason)
            router.note_trace(tracing.build_trace_record(
                trace_id=entry.trace_id, hop=0, role="router",
                finish_reason=reason, tokens=len(entry.tokens),
                failover_count=entry.failover_count,
                tokens_relayed=entry.tokens_relayed,
                e2e_s=time.perf_counter() - t0, error=error))

        def _generate_stream(self, body: dict) -> None:
            """Streamed /v1/generate with mid-stream failover: journal
            every relayed token; on replica death after first bytes,
            resume on a survivor via ``resume_tokens`` — the client
            stream continues with no error frame. Degradations (all
            end in an honest frame, never a silent truncation):
            journal over cap, failover retries exhausted, no surviving
            replica, resume rejected, deadline expiry."""
            t0 = time.perf_counter()
            try:
                deadline_t = self._client_deadline()
            except ValueError as e:
                self._json(400, {"error": str(e)})
                return
            entry = router.journal.open(body, deadline_t)
            entry.trace_id, entry.trace_sampled = \
                self._trace_context()
            if entry.trace_sampled:
                tracing.crumb("recv", entry.trace_id, 0)
            self._finish_reason = ""
            try:
                tried: set = set()
                opened = self._open_on_fleet(body, "/v1/generate",
                                             tried, affine=True,
                                             deadline_t=deadline_t,
                                             trace=entry)
                if opened[0] == "relay":
                    self._json(opened[1], opened[2])
                    return
                if opened[0] == "reject":
                    self._json(opened[1], opened[2],
                               headers=opened[3])
                    return
                _, resp, rep = opened
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                while True:
                    outcome = self._relay_journal_stream(entry, resp,
                                                         rep)
                    resp.close()
                    if outcome == "done":
                        router.observe_e2e(time.perf_counter() - t0,
                                           synthetic=bool(
                                               body.get("probe")))
                        self._close_trace(
                            entry, self._finish_reason or "done", t0)
                        return
                    if outcome == "client_gone":
                        flightrec.record(
                            "router", "client gone mid-stream")
                        self._close_trace(entry, "cancelled", t0,
                                          "client gone mid-stream")
                        return
                    if outcome == "deadline":
                        self._finish_frame(entry, "deadline")
                        self._close_trace(entry, "deadline", t0)
                        return
                    # outcome == "failed": the serving replica died
                    # (or wedged into eviction) mid-stream. This is a
                    # FAILOVER, not a pre-first-byte re-route —
                    # router_failovers_total (note_failover below) is
                    # its counter; only the per-replica failure
                    # accounting rides here.
                    tried.add(rep.name)
                    rep.note_failed()
                    router.replica_failed(rep)
                    if entry.over_cap:
                        self._finish_frame(
                            entry, "error",
                            "replica failed mid-stream past the "
                            f"failover journal cap "
                            f"({router.journal.max_tokens} tokens); "
                            "retry the request")
                        self._close_trace(entry, "error", t0,
                                          "journal over cap")
                        return
                    if entry.failover_count >= cfg.failover_retries:
                        self._finish_frame(
                            entry, "error",
                            "replica failed mid-stream and the "
                            f"failover budget "
                            f"({cfg.failover_retries}) is exhausted")
                        self._close_trace(entry, "error", t0,
                                          "failover budget exhausted")
                        return
                    if deadline_t is not None \
                            and time.monotonic() >= deadline_t:
                        self._finish_frame(entry, "deadline")
                        self._close_trace(entry, "deadline", t0)
                        return
                    router.journal.begin_failover(entry)
                    router.note_failover(rep,
                                         tokens=len(entry.tokens))
                    if entry.trace_sampled:
                        # The failover seam, on the ROUTER's clock:
                        # the timeline join pins the first hop's
                        # orphaned lifecycle closed here.
                        tracing.crumb("seam", entry.trace_id,
                                      entry.hop,
                                      tokens=len(entry.tokens),
                                      rep=rep.name)
                    opened = self._open_on_fleet(
                        entry.resume_body(), "/v1/generate", tried,
                        affine=True, deadline_t=deadline_t,
                        trace=entry)
                    if opened[0] != "resp":
                        router.journal.end_failover(entry)
                        detail = opened[2]
                        reason = ("deadline"
                                  if detail.get("error") == "deadline"
                                  else "error")
                        self._finish_frame(
                            entry, reason,
                            None if reason == "deadline" else
                            "replica failed mid-stream and no "
                            f"survivor could resume: {detail}")
                        self._close_trace(
                            entry, reason, t0,
                            "" if reason == "deadline" else
                            "no survivor could resume")
                        return
                    _, resp, rep = opened
                    # Resumed stream open: the request is in-flight on
                    # the survivor again (a graceful drain now covers
                    # it), so the failover window closes here.
                    router.journal.end_failover(entry)
            finally:
                router.journal.close(entry)

        def _relay_journal_stream(self, entry, resp, rep) -> str:
            """Relay one replica's ndjson stream, journaling every
            token. Returns ``done`` (final frame relayed), ``failed``
            (replica died / wedged-evicted / torn line — failover
            decision is the caller's), ``deadline`` (client budget
            expired while the stream was quiet), or ``client_gone``.

            Duplicate suppression at the kill seam: token events carry
            their index in the generated sequence (``i``, falling back
            to arrival order); an index below the journal length was
            already relayed by the previous replica — e.g. the token
            it emitted as it died — and is dropped, so the client sees
            every index exactly once."""
            reader = _StreamReader(resp)
            try:
                return self._relay_lines(entry, reader, rep)
            finally:
                reader.close()

        def _relay_lines(self, entry, reader, rep) -> str:
            base = len(entry.tokens)
            seen = 0
            while True:
                got = reader.get(_STREAM_POLL_S)
                if got is None:           # stream quiet: poll state
                    if rep.state in (rstate.DEAD, rstate.EVICTED):
                        flightrec.record(
                            "router",
                            f"stream owner {rep.name} evicted "
                            "mid-relay")
                        return "failed"
                    remaining = entry.remaining_ms()
                    if remaining is not None and remaining <= 0:
                        return "deadline"
                    continue
                kind, line = got
                if kind == "exc":
                    # Socket reset OR chunked framing cut mid-chunk
                    # (IncompleteRead) — both are the replica dying.
                    flightrec.record("router", "stream relay broke")
                    return "failed"
                if not line:
                    # EOF without a done frame: the replica's frontend
                    # died between tokens.
                    return "failed"
                try:
                    ev = json.loads(line)
                except ValueError:
                    # Torn line at the death seam: never relay bytes
                    # the journal cannot account for.
                    return "failed"
                if "token" in ev:
                    idx = ev.get("i")
                    if idx is None:
                        idx = base + seen
                    seen += 1
                    if idx < len(entry.tokens):
                        continue           # duplicate: suppress
                    router.journal.note_token(entry, ev["token"])
                    try:
                        self._chunk(line)
                    except OSError:
                        return "client_gone"
                    continue
                if ev.get("done"):
                    self._finish_reason = str(
                        ev.get("finish_reason") or "")
                    if entry.failover_count:
                        ev["failover_count"] = entry.failover_count
                        line = (json.dumps(ev) + "\n").encode()
                    try:
                        self._chunk(line)
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        return "client_gone"
                    return "done"
                # Unknown frame kinds relay verbatim (forward compat).
                try:
                    self._chunk(line)
                except OSError:
                    return "client_gone"

        def _relay_stream(self, resp) -> None:
            """Legacy (--no-failover) relay: replica ndjson chunk-by-
            chunk (urllib de-chunks the replica side; we re-chunk
            toward the client). A replica death mid-stream ends the
            stream with an error done-frame — tokens already forwarded
            cannot be unsent; the client retries and lands on a live
            replica."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                for line in resp:
                    self._chunk(line)
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                raise
            except (OSError, http.client.HTTPException):
                # Replica-side failure mid-relay: close the stream
                # honestly (the flight recorder notes it; the done
                # frame says error, not length).
                flightrec.record("router", "stream relay broke")
                try:
                    self._chunk(json.dumps(
                        {"done": True, "finish_reason": "error",
                         "error": "replica failed mid-stream"})
                        .encode() + b"\n")
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

    return Handler
