"""Bounded in-memory journal of in-flight streamed requests.

The enabling bookkeeping for mid-stream failover (docs/serving.md
"Mid-stream failover & serve-tier chaos"): for every streaming
``/v1/generate`` the frontend relays, the journal keeps the original
request body (prompt, sampling params, seed, budget) plus every token
id already relayed to the client. When the serving replica dies after
first bytes reached the client, that journal IS the resume state —
the frontend re-submits to a survivor with ``resume_tokens`` and the
client's ndjson stream continues where it stopped.

Bounds: one entry per in-flight stream, freed on finish (client done,
client gone, or abandonment); a stream that relays more than
``max_tokens`` tokens keeps streaming but loses failover protection
(``over_cap`` — on replica death it gets the honest error frame, the
documented degradation mode). Memory is therefore O(in-flight streams
x max_tokens), never O(history).

``active_failovers()`` feeds the drain path: a router drain waits for
in-flight failovers against the shared grace budget instead of
orphaning a journaled request with its frontend thread.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional

_ids = itertools.count(1)


class JournalEntry:
    """One in-flight streamed request's resume state. Mutated only by
    its owning frontend handler thread; read by the drain path."""

    __slots__ = ("id", "body", "tokens", "over_cap", "failover_count",
                 "deadline_t", "failing_over", "trace_id",
                 "trace_sampled", "hop", "tokens_relayed")

    def __init__(self, body: dict,
                 deadline_t: Optional[float] = None):
        self.id = next(_ids)
        # The resubmittable request: everything the client sent except
        # transport-level fields the relay re-derives.
        self.body = dict(body)
        self.tokens: List[int] = []
        self.over_cap = False
        self.failover_count = 0
        self.deadline_t = deadline_t
        self.failing_over = False
        # Trace context (tpunet/obs/tracing.py): the id travels on
        # every hop's headers — including failover re-submits, which
        # is why it lives HERE next to the resume state. ``hop``
        # counts replica opens (0 = router itself; each open / re-open
        # increments), so (trace_id, hop) names one process span.
        self.trace_id = ""
        self.trace_sampled = False
        self.hop = 0
        # Journal length at the LAST failover seam — what the
        # ``obs_trace`` router record reports as ``tokens_relayed``
        # (None until a failover happens).
        self.tokens_relayed: Optional[int] = None

    def remaining_ms(self,
                     now: Optional[float] = None) -> Optional[float]:
        """Milliseconds left of the client's deadline budget (None =
        no deadline; <= 0 = expired)."""
        if self.deadline_t is None:
            return None
        return 1e3 * (self.deadline_t
                      - (time.monotonic() if now is None else now))

    def resume_body(self) -> dict:
        """The failover re-submission: the original body plus the
        journaled continuation point."""
        body = dict(self.body)
        body["resume_tokens"] = list(self.tokens)
        body["stream"] = True
        return body


class RequestJournal:
    """Registry of in-flight journal entries (one router-wide
    instance, owned by the Router so the drain path can see it)."""

    def __init__(self, max_tokens: int = 4096):
        if max_tokens < 1:
            raise ValueError(
                f"failover_journal_tokens must be >= 1, "
                f"got {max_tokens}")
        self.max_tokens = max_tokens
        self._lock = threading.Lock()
        self._entries: Dict[int, JournalEntry] = {}

    def open(self, body: dict,
             deadline_t: Optional[float] = None) -> JournalEntry:
        entry = JournalEntry(body, deadline_t)
        with self._lock:
            self._entries[entry.id] = entry
        return entry

    def close(self, entry: JournalEntry) -> None:
        """Free the entry (stream finished or abandoned). Idempotent."""
        with self._lock:
            self._entries.pop(entry.id, None)
            entry.failing_over = False

    def note_token(self, entry: JournalEntry, token: int) -> bool:
        """Record one relayed token. Returns False once the entry is
        over the cap (the token is NOT recorded; the stream keeps
        relaying but is no longer failover-protected)."""
        if entry.over_cap:
            return False
        if len(entry.tokens) >= self.max_tokens:
            entry.over_cap = True
            return False
        entry.tokens.append(int(token))
        return True

    def begin_failover(self, entry: JournalEntry) -> None:
        entry.failover_count += 1
        entry.failing_over = True
        entry.tokens_relayed = len(entry.tokens)

    def end_failover(self, entry: JournalEntry) -> None:
        entry.failing_over = False

    def active(self) -> int:
        with self._lock:
            return len(self._entries)

    def active_failovers(self) -> int:
        """In-flight requests currently between a replica death and
        their resumed stream's completion — what a drain must wait
        for before it tears the replica set down."""
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if e.failing_over)
