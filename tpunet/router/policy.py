"""Hysteresis autoscale policy over fleet queue depth and TTFT SLO.

The scale signal is deliberately boring: *sustained* fleet queue
depth per slot (the backpressure number the serve docs already teach
operators to watch) plus, when a TTFT SLO is configured, the SLO
burn ratio (fleet TTFT p99 / SLO). Hysteresis comes from three
guards — a condition must hold for ``scale_window_probes``
consecutive probe rounds to fire, up- and down-thresholds are far
apart, and every action starts a ``scale_cooldown_s`` hold — so a
bursty queue cannot flap the fleet, and a scale-up (which takes
seconds thanks to AOT warm-start, but is never free) only happens
under pressure that is real.
"""

from __future__ import annotations

import time
from typing import Optional

SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"


class AutoscalePolicy:
    """Pure decision logic — no spawning, no probing; the Router's
    control loop feeds it one observation per probe round and acts on
    the decision it returns."""

    def __init__(self, cfg, *, clock=time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self._up_rounds = 0
        self._down_rounds = 0
        self._hold_until = 0.0
        self.last_decision = "hold"

    def slo_burn(self, ttft_p99_s: Optional[float]) -> Optional[float]:
        """TTFT SLO burn ratio (>1 = burning), or None when no SLO is
        configured or no sample exists."""
        if self.cfg.ttft_slo_ms <= 0 or ttft_p99_s is None:
            return None
        return ttft_p99_s / (self.cfg.ttft_slo_ms / 1e3)

    def observe(self, *, queue_depth: int, slots: int,
                ttft_p99_s: Optional[float],
                replicas: int) -> Optional[str]:
        """One probe-round observation -> SCALE_UP / SCALE_DOWN /
        None. ``replicas`` counts live (non-dead) replicas; min/max
        bounds and the cooldown are enforced here so the caller can
        act on any non-None return unconditionally."""
        now = self._clock()
        if slots <= 0:
            # No healthy capacity to measure (fleet still booting, or
            # everything dead): an empty queue here is ignorance, not
            # idleness — don't let boot time arm a scale-down.
            self._up_rounds = 0
            self._down_rounds = 0
            return None
        per_slot = queue_depth / slots
        burn = self.slo_burn(ttft_p99_s)
        pressure = per_slot >= self.cfg.scale_up_queue_per_slot \
            or (burn is not None and burn > 1.0)
        idle = per_slot <= self.cfg.scale_down_queue_per_slot \
            and (burn is None or burn < 1.0)
        self._up_rounds = self._up_rounds + 1 if pressure else 0
        self._down_rounds = self._down_rounds + 1 if idle else 0
        if now < self._hold_until:
            return None
        if self._up_rounds >= self.cfg.scale_window_probes \
                and replicas < self.cfg.max_replicas:
            self._fire(now)
            self.last_decision = SCALE_UP
            return SCALE_UP
        if self._down_rounds >= self.cfg.scale_window_probes \
                and replicas > self.cfg.min_replicas:
            self._fire(now)
            self.last_decision = SCALE_DOWN
            return SCALE_DOWN
        return None

    def _fire(self, now: float) -> None:
        self._up_rounds = 0
        self._down_rounds = 0
        self._hold_until = now + self.cfg.scale_cooldown_s
