"""Synthetic canary prober: known-answer requests through the fleet.

Passive metrics only see the traffic that arrives and only the
dimensions the servers measure about themselves. The prober is the
client's advocate inside the router process: on a fixed cadence
(``--probe-every-s``) it issues a pinned greedy request through the
ROUTER'S OWN public endpoint — the full proxy path: routing,
affinity, retries, journaling, mid-stream failover — and judges the
answer like a client would:

- **availability**: did a well-formed stream come back in time;
- **latency**: TTFT (first token line) and e2e, measured from the
  client side of the socket;
- **correctness**: are the tokens BITWISE identical to the golden
  sequence — the SLI no passive metric can see (a bad weight rollout
  serves fast, available, *wrong* tokens). The golden is the first
  clean probe's output: generation is greedy and deterministic, so
  every replica — and a mid-probe failover resume — must reproduce
  it exactly.

Every probe mints an ``X-Trace-Id`` (always adopted + sampled by the
frontend), so a failed or slow probe points at a replayable trace —
the id travels into the SLO engine and onto the page that follows.
Probe verdicts feed the same SLI streams as real traffic
(tpunet/obs/slo.py); the ``probe`` body marker keeps the frontend
from double-counting them in the passive feed.

The probe prompt rotates a ``session`` key so session affinity
spreads probes across the fleet instead of pinning them to one
replica's rendezvous slot; the token prompt itself never varies (the
golden depends on it).

The prober ARMS on its first clean probe (the one that sets the
golden): failures before that — the router booted faster than its
replicas, which is every cold start — count in
``prober_failures_total`` but do not feed the SLO engine. Boot
gating belongs to readiness checks; an error budget measures what
clients saw from a fleet that had come up.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import List, Optional

from tpunet.obs import flightrec, tracing

#: Pinned probe prompt: token ids kept tiny so the smallest test
#: vocabularies (31) accept them. Changing this invalidates goldens.
PROBE_PROMPT = (1, 2, 3, 5, 7, 11, 13, 2)

#: Tokens the probe asks for: long enough to cross a failover seam,
#: short enough to stay far under the overhead gate.
PROBE_NEW_TOKENS = 8

#: Distinct session keys probes rotate through (spreads probes over
#: the fleet's rendezvous slots).
PROBE_SESSIONS = 8


class Prober:
    """The prober thread. ``start()`` after the frontend listens
    (it needs the bound port); ``stop()`` before teardown."""

    def __init__(self, cfg, engine, *, registry,
                 base_url: str, clock=time.perf_counter):
        self.cfg = cfg
        self.engine = engine           # SloEngine (note_probe sink)
        self.registry = registry
        self.base_url = base_url.rstrip("/")
        self._clock = clock
        # Per-socket-op AND whole-probe budget: a stalled stream whose
        # individual lines stay under the socket timeout is still
        # failed when the probe as a whole runs past it.
        self.timeout_s = max(cfg.probe_timeout_s,
                             2.0 * cfg.probe_every_s)
        self.golden: Optional[List[int]] = None
        self.last_trace_id = ""
        self._n = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Prober":
        handle = flightrec.register_thread("router-prober",
                                           stall_after_s=120.0)
        flightrec.record("router",
                         f"prober start every={self.cfg.probe_every_s}s"
                         f" timeout={self.timeout_s:.2f}s")

        def run() -> None:
            while not self._stop.is_set():
                handle.beat("busy")
                try:
                    self.probe_once()
                except Exception as e:  # noqa: BLE001 — a prober crash
                    # must never take the router down; the failed
                    # probe is itself the signal.
                    flightrec.record("router", f"prober error: {e}")
                    if self.golden is not None:   # armed (see module
                        self.engine.note_probe(   # docstring)
                            ok=False, trace_id=self.last_trace_id)
                handle.beat("idle")
                self._stop.wait(self.cfg.probe_every_s)

        self._thread = threading.Thread(
            target=run, daemon=True, name="tpunet-router-prober")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + 1.0)

    # -- one probe -------------------------------------------------------

    def _body(self) -> dict:
        self._n += 1
        return {"tokens": list(PROBE_PROMPT),
                "max_new_tokens": PROBE_NEW_TOKENS,
                "stream": True,
                # Greedy + pinned seed: bitwise-reproducible across
                # replicas and across a mid-probe failover resume.
                "temperature": 0.0, "seed": 7,
                "session": f"slo-probe-{self._n % PROBE_SESSIONS}",
                "probe": True}

    def probe_once(self) -> bool:
        """Issue one probe and feed the verdict to the registry and
        the SLO engine. Returns the availability verdict."""
        trace_id = tracing.mint_trace_id()
        self.last_trace_id = trace_id
        self.registry.counter("prober_requests_total").inc()
        t0 = self._clock()
        deadline = t0 + self.timeout_s
        ok = False
        mismatch = False
        ttft_s: Optional[float] = None
        tokens: List[int] = []
        req = urllib.request.Request(
            self.base_url + "/v1/generate",
            json.dumps(self._body()).encode(),
            {"Content-Type": "application/json",
             tracing.TRACE_HEADER: trace_id})
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
            try:
                for line in resp:
                    now = self._clock()
                    if now > deadline:
                        break                    # wedged mid-stream
                    ev = json.loads(line)
                    if "token" in ev:
                        if ttft_s is None:
                            ttft_s = now - t0
                        tokens.append(int(ev["token"]))
                        continue
                    if ev.get("done"):
                        ok = not ev.get("error") \
                            and ev.get("finish_reason") \
                            not in ("error", "deadline")
                        break
            finally:
                resp.close()
        except Exception:  # noqa: BLE001 — timeout, refused, torn
            ok = False     # stream: all the same availability verdict
        e2e_s = self._clock() - t0
        if ok and not tokens:
            ok = False                 # a done frame with no tokens
        if ok:
            if self.golden is None:
                self.golden = list(tokens)
                flightrec.record(
                    "router", f"prober golden set n={len(tokens)}")
            elif tokens != self.golden:
                mismatch = True
                self.registry.counter("prober_mismatch_total").inc()
                flightrec.record(
                    "router",
                    f"prober GOLDEN MISMATCH trace={trace_id}")
            self.registry.histogram("prober_e2e_s").observe(e2e_s)
            if ttft_s is not None:
                self.registry.histogram("prober_ttft_s").observe(
                    ttft_s)
        else:
            self.registry.counter("prober_failures_total").inc()
        if ok or self.golden is not None:   # warmup gate: unarmed
            self.engine.note_probe(         # failures don't burn
                ok=ok, mismatch=mismatch, ttft_s=ttft_s,
                e2e_s=e2e_s if ok else None, trace_id=trace_id)
        return ok
