"""The ``obs_router`` record builders (docs/metrics_schema.md).

Module-level and engine-free, like ``build_serve_record``: the
schema-conformance check (scripts/check_metrics_schema.py) drives the
exact record shapes without standing up a router. Two flavors share
the kind:

- **window records** (``build_router_record``) — periodic fleet
  state: cumulative counters + window histograms + per-replica rows.
  No ``event`` field; they never page.
- **event records** (``build_router_event``) — one per action the
  control loop (or the failover relay) takes (evict / respawn /
  scale_up / scale_down / failover). These carry ``event`` and DO
  page through the alert webhook (tpunet/obs/export/webhook.py).
"""

from __future__ import annotations

import time
from typing import List, Optional

from tpunet.router import replica as replica_states


def build_router_record(reg, *, replicas: List[dict], uptime_s: float,
                        window_s: float, scale_decision: str = "hold",
                        ttft_slo_burn: Optional[float] = None,
                        final: bool = False) -> dict:
    """One ``obs_router`` window record from the registry + the
    per-replica ``view()`` rows."""
    by_state = {}
    for row in replicas:
        by_state[row["state"]] = by_state.get(row["state"], 0) + 1
    healthy = [r for r in replicas
               if r["state"] == replica_states.HEALTHY]
    record = {
        "uptime_s": round(uptime_s, 3),
        "window_s": round(window_s, 3),
        "replicas": len(replicas),
        "replicas_healthy": by_state.get(replica_states.HEALTHY, 0),
        "replicas_draining": by_state.get(replica_states.DRAINING, 0),
        "replicas_dead": (by_state.get(replica_states.DEAD, 0)
                          + by_state.get(replica_states.EVICTED, 0)),
        "fleet_queue_depth": sum(r["queue_depth"] for r in healthy),
        "fleet_active_slots": sum(r["active_slots"] for r in healthy),
        "fleet_slots": sum(r["slots"] for r in healthy),
        "scale_decision": scale_decision,
    }
    for name in ("requests", "rerouted", "rejected", "affinity_hits",
                 "failovers", "evictions", "respawns", "scale_ups",
                 "scale_downs", "probe_failures"):
        record[f"{name}_total"] = int(
            reg.counter(f"router_{name}_total").value)
    if ttft_slo_burn is not None:
        record["ttft_slo_burn"] = round(ttft_slo_burn, 4)
    hist = reg.histogram("router_e2e_s")
    summ = hist.summary()
    for stat in ("p50", "p90", "p99", "mean"):
        if stat in summ:
            record[f"e2e_{stat}_s"] = round(summ[stat], 6)
    if summ:
        record["e2e_count"] = int(summ["count"])
        record["e2e_sample"] = [round(v, 6)
                                for v in hist.export_sample()]
        if summ.get("approx"):
            record["e2e_approx"] = 1
    record["per_replica"] = replicas
    if final:
        record["final"] = True
    return record


def build_router_event(event: str, *, replica: str = "",
                       url: str = "", cause: str = "",
                       old_replicas: Optional[int] = None,
                       new_replicas: Optional[int] = None,
                       detail: Optional[dict] = None) -> dict:
    """One ``obs_router`` action event (pages through the alert
    webhook). ``cause`` says what triggered it: ``probe_failures``,
    ``webhook:<reason>`` (an AlertWebhook page consumed on
    POST /webhook), or ``policy`` (an autoscale decision)."""
    record: dict = {"event": event, "severity": "warn",
                    "time": time.time()}
    if replica:
        record["replica"] = replica
    if url:
        record["url"] = url
    if cause:
        record["cause"] = cause
    if old_replicas is not None:
        record["old_replicas"] = old_replicas
    if new_replicas is not None:
        record["new_replicas"] = new_replicas
    if detail is not None:
        record["detail"] = detail
    return record
