"""Per-replica handle: state machine + live load probes.

One ``ReplicaHandle`` per backend replica, whether the router spawned
it (supervisor mode) or was pointed at it (``--replica URL``). The
probe loop drives the state machine; the balancer reads
``routable()`` and ``load_score()``; the frontend counts routed
requests on it.

States::

    STARTING --probe ok--> HEALTHY --drain 503--> DRAINING
        HEALTHY --probe fail x unhealthy_after--> DEAD
        HEALTHY --webhook page / operator--> EVICTED
        DRAINING --Retry-After elapsed + probe ok--> HEALTHY
        DEAD/EVICTED --supervisor respawn--> STARTING

Probes hit ``/healthz`` (liveness, slots, run_id — the join key
webhook pages are matched on) and ``/metrics`` (the
``serve_queue_depth`` / ``serve_active_slots`` gauges plus the
cumulative ``serve_requests_total`` the least-loaded tests assert
on). A replica mid-drain answers 503 with ``Retry-After``; the
handle backs off routing for exactly that long instead of hammering
a shutdown with requests it will reject.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

STARTING = "starting"
HEALTHY = "healthy"
DRAINING = "draining"
DEAD = "dead"
EVICTED = "evicted"

#: States the balancer may route to (STARTING is excluded: the engine
#: may still be compiling; the first successful probe promotes it).
_ROUTABLE = (HEALTHY,)


class ReplicaHandle:
    """Router-side view of one serving replica."""

    def __init__(self, name: str, url: str, *,
                 clock=time.monotonic):
        self.name = name
        self.url = url.rstrip("/")
        self.state = STARTING
        self.run_id = ""
        self._clock = clock
        self._lock = threading.Lock()
        # Latest probe snapshot.
        self.slots = 0
        self.queue_depth = 0
        self.active_slots = 0
        self.serve_requests_total = 0
        self.ttft_p99_s: Optional[float] = None
        self.last_probe_t: Optional[float] = None
        self.fail_streak = 0
        self.backoff_until = 0.0
        # Router-side accounting.
        self.requests_routed = 0
        self.requests_failed = 0

    # -- balancer view ---------------------------------------------------

    def routable(self, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        return self.state in _ROUTABLE and now >= self.backoff_until

    def load_score(self) -> float:
        """Queued + in-flight work per slot — the least-loaded metric.
        Unknown capacity scores worst so a never-probed replica is
        only picked when nothing better exists."""
        if self.slots <= 0:
            return float("inf")
        return (self.queue_depth + self.active_slots) / self.slots

    def note_routed(self) -> None:
        with self._lock:
            self.requests_routed += 1
            # Optimistic local bump so a burst routed between two
            # probes spreads instead of dogpiling one replica.
            self.active_slots = min(self.active_slots + 1,
                                    max(self.slots, 1))

    def note_failed(self) -> None:
        with self._lock:
            self.requests_failed += 1

    def backoff(self, seconds: float) -> None:
        """Stop routing here for ``seconds`` (drain Retry-After, or a
        429 burst)."""
        with self._lock:
            self.backoff_until = max(self.backoff_until,
                                     self._clock() + seconds)

    # -- probing ---------------------------------------------------------

    def probe(self, timeout: float = 2.0) -> bool:
        """One health+load probe. Returns True when the replica
        answered (healthy OR draining); False on a hard failure
        (connection refused / timeout / 5xx-unhealthy)."""
        try:
            health = self._get_json("/healthz", timeout)
        except _Draining as d:
            with self._lock:
                if self.state in (HEALTHY, STARTING):
                    self.state = DRAINING
                if d.retry_after > 0:
                    self.backoff_until = max(
                        self.backoff_until, self._clock() + d.retry_after)
                self.fail_streak = 0
                self.last_probe_t = self._clock()
                if d.run_id:
                    self.run_id = d.run_id
            return True
        except Exception:  # noqa: BLE001 — any transport failure is
            # the same signal: the replica did not answer.
            with self._lock:
                self.fail_streak += 1
            return False
        with self._lock:
            self.run_id = health.get("run_id") or self.run_id
            self.slots = int(health.get("slots") or self.slots or 0)
            self.queue_depth = int(health.get("queue_depth") or 0)
            self.active_slots = int(health.get("active_slots") or 0)
            self.fail_streak = 0
            self.last_probe_t = self._clock()
            if self.state in (STARTING, DRAINING, DEAD):
                # DEAD recovers on a good probe: an external replica
                # the operator restarted on the same URL rejoins
                # without router surgery (EVICTED does not — a page
                # named it bad; only a respawn resets it).
                self.state = HEALTHY
        # Load gauges + cumulative counters from /metrics — the
        # snapshot is authoritative for occupancy (healthz numbers
        # ride along for capacity); a failed metrics read is not a
        # health failure.
        try:
            snap = self._get_json("/metrics", timeout)
            with self._lock:
                if "serve_queue_depth" in snap:
                    self.queue_depth = int(snap["serve_queue_depth"])
                if "serve_active_slots" in snap:
                    self.active_slots = int(snap["serve_active_slots"])
                self.serve_requests_total = int(
                    snap.get("serve_requests_total", 0))
                if snap.get("serve_ttft_s_p99") is not None:
                    self.ttft_p99_s = float(snap["serve_ttft_s_p99"])
        except Exception:  # noqa: BLE001
            pass
        return True

    def _get_json(self, path: str, timeout: float) -> dict:
        try:
            with urllib.request.urlopen(self.url + path,
                                        timeout=timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read())
            except Exception:  # noqa: BLE001
                pass
            if e.code == 503 and body.get("status") == "draining":
                raise _Draining(
                    retry_after=float(e.headers.get("Retry-After") or 0),
                    run_id=body.get("run_id") or "")
            raise

    # -- lifecycle -------------------------------------------------------

    def mark(self, state: str) -> None:
        with self._lock:
            self.state = state

    def reset_for_respawn(self, url: Optional[str] = None) -> None:
        """Back to STARTING with fresh probe state (the supervisor
        respawned the process behind this handle, possibly on a new
        port)."""
        with self._lock:
            if url is not None:
                self.url = url.rstrip("/")
            self.state = STARTING
            self.run_id = ""
            self.fail_streak = 0
            self.backoff_until = 0.0
            self.queue_depth = 0
            self.active_slots = 0
            self.serve_requests_total = 0
            self.ttft_p99_s = None

    def view(self) -> dict:
        """JSON-able row for ``GET /replicas`` and the per-replica
        list on ``obs_router`` records."""
        with self._lock:
            return {
                "name": self.name, "url": self.url,
                "state": self.state, "run_id": self.run_id,
                "slots": self.slots, "queue_depth": self.queue_depth,
                "active_slots": self.active_slots,
                "serve_requests_total": self.serve_requests_total,
                "requests_routed": self.requests_routed,
                "requests_failed": self.requests_failed,
                "fail_streak": self.fail_streak,
            }


class _Draining(Exception):
    """Internal probe signal: the replica answered 503-draining."""

    def __init__(self, retry_after: float = 0.0, run_id: str = ""):
        super().__init__("draining")
        self.retry_after = retry_after
        self.run_id = run_id
