"""Replica process lifecycle: spawn, drain-then-restart, respawn.

Supervisor mode is what turns the router from a proxy into a fleet
operator: it launches ``python -m tpunet.serve`` children (one per
replica slot), restarts the ones the control loop evicts, and scales
the set up/down on the policy's decisions. Children always get
``--aot-cache`` pointed at a shared store when the router has one —
a respawned replica deserializes its compiled programs instead of
recompiling, which is the difference between a seconds-scale and a
minutes-scale recovery (docs/serving.md "AOT warm-start").

Stopping is drain-then-kill: SIGTERM triggers the serve entry's
graceful drain (in-flight streams finish, the final ``obs_serve``
record flushes), and only a child still alive after ``drain_grace_s``
gets SIGKILL. Each child's stdout/stderr lands in
``<dir>/replica-<i>.log`` next to its own metrics dir, so a dead
replica leaves its flight-recorder crash report and its log where
the operator (and ``scripts/obs_crash_report.py``) can find them.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from tpunet.obs.flightrec import register_thread


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-then-close; the tiny race
    window is acceptable for dev/test replica fleets — production
    deployments pin ports)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class ReplicaProcess:
    """One spawned serve child."""

    def __init__(self, index: int, port: int, proc: subprocess.Popen,
                 run_id: str, log_path: str):
        self.index = index
        self.port = port
        self.proc = proc
        self.run_id = run_id
        self.log_path = log_path
        self.spawned_t = time.monotonic()

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None


class Supervisor:
    """Spawns and reaps ``python -m tpunet.serve`` replica children.

    ``serve_args`` is the passthrough argv tail (model architecture,
    checkpoint dir, slots...) every child shares; per-child --port,
    --run-id and --metrics-dir are appended here. The supervisor
    itself is single-threaded (the router's control loop drives it)
    but registers in the flightrec host-thread registry so the
    processes it owns are inventoried next to every other background
    resource."""

    def __init__(self, serve_args: List[str], *, directory: str = "",
                 host: str = "127.0.0.1", drain_grace_s: float = 30.0,
                 run_prefix: str = "router-replica",
                 aot_cache: str = "", chaos: str = ""):
        self.serve_args = list(serve_args)
        self.directory = directory
        self.host = host
        self.drain_grace_s = drain_grace_s
        self.run_prefix = run_prefix
        self.aot_cache = aot_cache
        # Router-level chaos spec (tpunet/serve/chaos.py grammar plus
        # the ``replica=I`` scope key): each child is launched with
        # exactly the events that address its index. A respawned
        # child re-arms its events — its counters restart with it.
        self.chaos = chaos
        self.spawned_total = 0
        self._procs: Dict[int, ReplicaProcess] = {}
        # Inventory-only registration (stall budget 0): the supervisor
        # has no thread of its own — the control loop beats for it —
        # but its children must be discoverable in crash reports.
        self._handle = register_thread("router-supervisor")

    def child_argv(self, index: int, port: int, run_id: str) -> List[str]:
        argv = [sys.executable, "-m", "tpunet.serve",
                "--host", self.host, "--port", str(port),
                "--run-id", run_id]
        if self.directory:
            argv += ["--metrics-dir",
                     os.path.join(self.directory, f"replica-{index}")]
        if self.aot_cache and "--aot-cache" not in self.serve_args:
            argv += ["--aot-cache", self.aot_cache]
        if self.chaos and "--chaos" not in self.serve_args:
            from tpunet.serve.chaos import spec_for_replica
            spec = spec_for_replica(self.chaos, index)
            if spec:
                argv += ["--chaos", spec]
        return argv + self.serve_args

    def spawn(self, index: int,
              port: Optional[int] = None) -> ReplicaProcess:
        """Launch replica ``index`` (an OS-assigned port unless
        pinned) and return its process record. The caller polls the
        replica's /healthz for readiness — spawn never blocks on the
        child's compile."""
        port = port if port else free_port(self.host)
        run_id = f"{self.run_prefix}-{index}"
        log_path = ""
        stdout = subprocess.DEVNULL
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            if self.aot_cache:
                os.makedirs(self.aot_cache, exist_ok=True)
            log_path = os.path.join(self.directory,
                                    f"replica-{index}.log")
            stdout = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                self.child_argv(index, port, run_id),
                stdout=stdout, stderr=subprocess.STDOUT,
                start_new_session=True)
        finally:
            if stdout is not subprocess.DEVNULL:
                stdout.close()
        record = ReplicaProcess(index, port, proc, run_id, log_path)
        self._procs[index] = record
        self.spawned_total += 1
        self._handle.beat("idle")
        return record

    def get(self, index: int) -> Optional[ReplicaProcess]:
        return self._procs.get(index)

    def stop(self, index: int, *, drain: bool = True,
             grace_s: Optional[float] = None) -> bool:
        """Drain-then-stop one child. Returns True when it exited
        inside the grace budget (False = SIGKILL was needed)."""
        record = self._procs.get(index)
        if record is None or not record.alive():
            return True
        grace = self.drain_grace_s if grace_s is None else grace_s
        clean = True
        if drain and grace > 0:
            try:
                record.proc.send_signal(signal.SIGTERM)
            except OSError:
                return True
            try:
                record.proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                clean = False
        else:
            clean = False
        if record.alive():
            try:
                record.proc.kill()
            except OSError:
                pass
            try:
                record.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
        return clean

    def kill(self, index: int) -> None:
        """Immediate SIGKILL (eviction of a wedged/crashed child —
        drain would block on a dead engine)."""
        self.stop(index, drain=False)

    def respawn(self, index: int) -> ReplicaProcess:
        """Stop (if needed) and relaunch replica ``index`` on a fresh
        port."""
        self.kill(index)
        return self.spawn(index)

    def stop_all(self, *, drain: bool = True,
                 grace_s: Optional[float] = None) -> None:
        """Stop every child against ONE shared grace budget: SIGTERM
        them all first, then wait — shutdown latency is one drain,
        not N sequential ones. ``grace_s`` overrides the budget (the
        router's drain passes what remains after waiting out in-flight
        failovers, so the whole shutdown honors ``drain_grace_s``
        once)."""
        grace = self.drain_grace_s if grace_s is None else grace_s
        alive = [r for r in self._procs.values() if r.alive()]
        if drain and grace > 0:
            for record in alive:
                try:
                    record.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
            deadline = time.monotonic() + grace
            for record in alive:
                remaining = deadline - time.monotonic()
                if remaining > 0:
                    try:
                        record.proc.wait(timeout=remaining)
                    except subprocess.TimeoutExpired:
                        pass
        for record in alive:
            if record.alive():
                try:
                    record.proc.kill()
                except OSError:
                    pass
                try:
                    record.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass

    def remove(self, index: int) -> None:
        self.stop(index, drain=True)
        self._procs.pop(index, None)
