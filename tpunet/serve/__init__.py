"""Production inference serving (``python -m tpunet.serve``).

The reference ships serving as a single-request Gradio demo
(GROUP03.pdf pp. 22-23; ``tpunet/infer/app.py`` keeps that shape as
the parity artifact). This package is the heavy-traffic path the
ROADMAP north star asks for — on TPU that means ONE resident jitted
decode program amortized across many in-flight requests instead of a
compiled forward per request:

- ``engine``    — continuous batching over a fixed pool of KV-cache
  slots: requests are admitted into free slots, prefilled through a
  bucketed chunked-prefill program, then decoded TOGETHER every
  iteration with per-slot positions and active masks; new requests
  join mid-flight, finished ones free their slot, and the compile
  count is bounded at 1 decode + len(prefill_buckets) programs.
- ``scheduler`` — bounded FIFO admission with backpressure (reject
  with queue-full rather than grow latency), per-request deadlines and
  cooperative cancellation.
- ``classify``  — micro-batched classifier path: concurrent
  ``/v1/classify`` requests coalesce into one jitted batched forward.
- ``frontend``  — stdlib-only threaded HTTP server: ``/v1/generate``
  (optionally streamed as ndjson), ``/v1/classify``, ``/healthz``,
  ``/metrics``; graceful drain on SIGTERM.

SLO metrics (serve_* counters/gauges/histograms, ``obs_serve``
records) flow through the existing ``tpunet/obs`` registry, sinks and
exporters — docs/serving.md and docs/metrics_schema.md document the
contract.
"""

from __future__ import annotations

from tpunet.serve.classify import ClassifyBatcher
from tpunet.serve.engine import Engine, PromptTooLongError, sample_token
from tpunet.serve.frontend import ServeServer
from tpunet.serve.scheduler import (DrainingError, GenerateRequest,
                                    QueueFullError, RequestQueue)

__all__ = [
    "ClassifyBatcher", "DrainingError", "Engine", "GenerateRequest",
    "PromptTooLongError", "QueueFullError", "RequestQueue",
    "ServeServer", "sample_token",
]
