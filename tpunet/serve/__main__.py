"""CLI entry point: ``python -m tpunet.serve --checkpoint-dir ...``.

Loads the LM family best checkpoint through the same
``infer.generate.load_lm`` path the generation CLI uses (pipeline
checkpoints unstack, tensor-parallel serving via ``--mesh-model``),
optionally a classifier checkpoint for the micro-batched
``/v1/classify`` path, wires the obs registry into ``metrics.jsonl``
and any configured live exporters, and serves until SIGTERM/SIGINT —
which triggers a graceful drain (stop admitting, finish in-flight,
flush telemetry) rather than dropping connections.
"""

from __future__ import annotations

import signal
import sys


def parse_prefill_buckets(spec, max_seq_len: int):
    """Validate ``--prefill-buckets``: comma-separated positive ints,
    none beyond ``--max-seq-len``. A bad entry is a LOUD exit-2 usage
    error — silently filtering a typo'd bucket used to change the
    server's compile set (and reject prompts) without a word."""
    entries = [e.strip() for e in str(spec).split(",") if e.strip()]
    if not entries:
        raise _usage(f"--prefill-buckets {spec!r} names no buckets; "
                     "give at least one padded prompt length, e.g. "
                     "--prefill-buckets 64,256,1024")
    buckets = []
    for raw in entries:
        try:
            bucket = int(raw)
        except ValueError:
            raise _usage(
                f"--prefill-buckets entry {raw!r} is not an integer "
                f"(got {spec!r}; expected comma-separated prompt-"
                "length buckets like 64,256,1024)")
        if bucket < 1:
            raise _usage(f"--prefill-buckets entry {bucket} must be "
                         ">= 1")
        if bucket > max_seq_len:
            raise _usage(
                f"--prefill-buckets entry {bucket} exceeds "
                f"--max-seq-len {max_seq_len}: the KV pool cannot "
                "hold a prompt that long — raise --max-seq-len or "
                "drop the bucket")
        buckets.append(bucket)
    return tuple(buckets)


def _usage(msg: str) -> SystemExit:
    print(f"python -m tpunet.serve: error: {msg}", file=sys.stderr,
          flush=True)
    return SystemExit(2)


def build_argparser():
    import argparse

    from tpunet.config import ServeConfig

    d = ServeConfig()
    p = argparse.ArgumentParser(
        prog="python -m tpunet.serve",
        description="tpunet production inference server")
    p.add_argument("--checkpoint-dir", default="checkpoints",
                   help="LM best-checkpoint directory (infer.generate "
                        "load_lm path)")
    p.add_argument("--host", default=d.host)
    p.add_argument("--port", type=int, default=d.port)
    p.add_argument("--slots", type=int, default=d.slots,
                   help="KV-slot pool size = max in-flight decodes")
    p.add_argument("--queue-max", type=int, default=d.queue_max,
                   help="bounded admission queue; beyond it requests "
                        "are rejected 429 (backpressure)")
    p.add_argument("--prefill-buckets", default=",".join(
        str(b) for b in d.prefill_buckets),
        help="comma-separated padded prompt-length buckets (compile "
             "count = number of buckets)")
    p.add_argument("--paged-kv", default=d.paged_kv,
                   action=argparse.BooleanOptionalAction,
                   help="paged KV cache (default on): K/V in a shared "
                        "page pool with per-slot page tables, so a "
                        "slot costs prompt-proportional HBM; "
                        "--no-paged-kv restores the dense "
                        "[slots, max_seq_len] pool")
    p.add_argument("--kv-pages", type=int, default=d.kv_pages,
                   help="usable KV pages in the shared pool (0 = "
                        "dense-equivalent capacity: slots x "
                        "ceil(max-seq-len / kv-page-tokens)); size it "
                        "down to oversubscribe slots against typical "
                        "request lengths")
    p.add_argument("--kv-page-tokens", type=int,
                   default=d.kv_page_tokens,
                   help="tokens per KV page (allocation granule)")
    p.add_argument("--kv-dtype", default=d.kv_dtype,
                   choices=["auto", "bf16", "int8"],
                   help="KV page payload dtype: auto = compute dtype; "
                        "bf16 halves float32 payloads; int8 "
                        "quantizes per written token row (float32 "
                        "scale stored with the page, eval-parity-"
                        "gated) — halves page cost again")
    p.add_argument("--prefix-cache", default=d.prefix_cache,
                   action=argparse.BooleanOptionalAction,
                   help="prefix KV cache (default on, paged only): "
                        "finished prefill pages stay in the pool as "
                        "refcounted content-addressed objects; a new "
                        "request pins its longest cached page-aligned "
                        "prefix and prefills only the suffix (COW at "
                        "the divergence page, LRU-evicted under pool "
                        "pressure)")
    p.add_argument("--prefix-cache-pages", type=int,
                   default=d.prefix_cache_pages,
                   help="pool pages the prefix cache may hold (0 = "
                        "half the usable pool) — bounded below the "
                        "pool so cached pages never starve paying "
                        "slots")
    p.add_argument("--prefix-store", default=d.prefix_store,
                   metavar="DIR",
                   help="shared-filesystem prefix spill/warm-start: "
                        "cached pages publish under DIR (first-writer-"
                        "wins, like --aot-cache) and a respawned "
                        "replica adopts the fleet's prefix set at "
                        "boot; entries scoped by model config + kv "
                        "levers so a lever change is a clean miss")
    p.add_argument("--spec-decode", default=d.spec_decode,
                   action=argparse.BooleanOptionalAction,
                   help="speculative decoding (default off, needs "
                        "paged KV + device sampling): a narrow "
                        "drafter proposes --spec-k tokens per slot "
                        "against its own paged pool, ONE wide verify "
                        "over the main pool scores them, rejection "
                        "rewinds the page-table cursor — output is "
                        "bitwise-identical to spec-off at any "
                        "acceptance rate (docs/serving.md)")
    p.add_argument("--spec-k", type=int, default=d.spec_k,
                   help="draft tokens per verify cycle (a slot emits "
                        "1..K+1 verified tokens per cycle)")
    p.add_argument("--spec-draft-width-mult", type=float,
                   default=d.spec_draft_width_mult,
                   help="drafter width as a fraction of the serving "
                        "model's hidden dim (floored to a multiple "
                        "of the head count; 1.0 = self-speculation "
                        "for parity testing)")
    p.add_argument("--spec-draft-checkpoint", default=d.
                   spec_draft_checkpoint, metavar="NPZ",
                   help="fitted drafter weights (tpunet.serve.spec."
                        "save_drafter_params npz); empty = "
                        "deterministic random init, which is correct "
                        "but drafts nothing useful — fit one against "
                        "real traffic with tpunet.serve.spec."
                        "fit_drafter")
    p.add_argument("--device-sampling", default=d.device_sampling,
                   action=argparse.BooleanOptionalAction,
                   help="batched temperature/top-k/top-p sampling "
                        "fused onto the decode step on device "
                        "(default on); --no-device-sampling restores "
                        "the host-side per-slot sampler")
    p.add_argument("--max-new-tokens", type=int,
                   default=d.default_max_new_tokens,
                   help="default per-request generation budget")
    p.add_argument("--max-new-tokens-cap", type=int,
                   default=d.max_new_tokens_cap,
                   help="hard per-request generation ceiling: larger "
                        "asks are clamped to it at admission")
    p.add_argument("--deadline-s", type=float,
                   default=d.default_deadline_s,
                   help="default per-request wall-clock deadline "
                        "(0 = none)")
    p.add_argument("--classify-batch-max", type=int,
                   default=d.classify_batch_max)
    p.add_argument("--classify-window-ms", type=float,
                   default=d.classify_window_ms)
    p.add_argument("--emit-every-s", type=float, default=d.emit_every_s,
                   help="obs_serve record cadence into metrics.jsonl")
    p.add_argument("--drain-timeout-s", type=float,
                   default=d.drain_timeout_s)
    p.add_argument("--metrics-dir", default="",
                   help="directory for metrics.jsonl (default: the "
                        "checkpoint dir); obs records share the "
                        "docs/metrics_schema.md contract")
    p.add_argument("--statsd", default="", metavar="HOST:PORT",
                   help="stream obs_serve records as statsd/UDP gauges")
    p.add_argument("--obs-http", default="", metavar="URL",
                   help="POST obs_serve records as line-JSON")
    p.add_argument("--obs-webhook", default="", metavar="URL",
                   help="POST one templated JSON payload per alert "
                        "record (obs_alert/obs_crash) — wire format "
                        "in docs/metrics_schema.md")
    p.add_argument("--run-id", default=d.run_id,
                   help="replica identity stamped on obs_serve records "
                        "(fleet rollups route by it; default "
                        "serve-<host>-<pid>)")
    p.add_argument("--chaos", default=d.chaos, metavar="SPEC",
                   help="serve-tier fault injection (tpunet/serve/"
                        "chaos.py): kill@tokens=N, kill@prefill[=K], "
                        "stall@tokens=N:ms=M, drop-probe@prob=P:"
                        "seed=X, slow-stream@ms=M — deterministic, "
                        "';'-separated; docs/serving.md grammar")
    p.add_argument("--trace-sample", type=float,
                   default=d.trace_sample, metavar="RATE",
                   help="standalone request-tracing head-sample rate "
                        "in [0,1] (tpunet/obs/tracing.py): applies to "
                        "requests WITHOUT router trace headers; a "
                        "client-supplied X-Trace-Id is always sampled"
                        " (default 0 = header-carried traces only)")
    p.add_argument("--aot-cache", default=d.aot_cache, metavar="DIR",
                   help="AOT warm-start: serialize the compiled decode"
                        " + prefill executables under DIR on first "
                        "boot and deserialize them on later boots — "
                        "replica cold-start drops from compile-bound "
                        "to seconds (single-device replicas; the "
                        "persistent compilation cache covers the rest)")
    # LM architecture (must match the trained checkpoint) — mirrors
    # tpunet.infer.generate's flags.
    p.add_argument("--model", choices=("lm", "lm_pp"), default="lm")
    p.add_argument("--vit-hidden", type=int, default=192)
    p.add_argument("--vit-depth", type=int, default=6)
    p.add_argument("--vit-heads", type=int, default=3)
    p.add_argument("--vocab-size", type=int, default=256)
    p.add_argument("--max-seq-len", type=int, default=1024)
    p.add_argument("--moe-experts", type=int, default=0)
    p.add_argument("--moe-every", type=int, default=2)
    p.add_argument("--moe-top-k", type=int, default=2)
    p.add_argument("--moe-capacity-factor", type=float, default=1.25)
    p.add_argument("--mesh-model", type=int, default=0,
                   help="tensor-parallel serving: shard block weights "
                        "and the KV pool's head dim over N devices")
    p.add_argument("--train-pipe", type=int, default=0)
    p.add_argument("--pp-virtual", type=int, default=2)
    # Optional classifier endpoint.
    p.add_argument("--classifier-checkpoint-dir", default="",
                   help="also serve /v1/classify from this MobileNetV2/"
                        "ViT best checkpoint (micro-batched)")
    p.add_argument("--classifier-model", default="mobilenet_v2")
    p.add_argument("--classifier-image-size", type=int, default=224)
    return p


def build_server(args):
    """Construct (but do not start) the ServeServer from parsed args —
    shared by main() and tests."""
    # Validate the pure-CLI surface BEFORE the jax-importing block
    # below: a typo'd bucket list should exit 2 in milliseconds, not
    # after a runtime import.
    buckets = parse_prefill_buckets(args.prefill_buckets,
                                    args.max_seq_len)
    if args.chaos:
        # Same posture as the bucket list: a typo'd chaos spec is a
        # loud exit-2 BEFORE the model loads, not a mid-serve raise.
        from tpunet.serve.chaos import ServeChaos, ServeChaosError
        try:
            ServeChaos.parse(args.chaos)
        except ServeChaosError as e:
            raise _usage(str(e))

    import dataclasses

    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, ServeConfig)
    from tpunet.infer.generate import load_lm
    from tpunet.obs.registry import JsonlSink
    from tpunet.serve.classify import ClassifyBatcher
    from tpunet.serve.engine import Engine
    from tpunet.serve.frontend import ServeServer
    from tpunet.utils.logging import MetricsLogger

    # Shared persistent compilation cache (tpunet/utils/cache.py):
    # even a replica without --aot-cache warm-starts its compiles from
    # the per-user cache dir the training/test entry points already
    # populate (JAX_COMPILATION_CACHE_DIR wins when set).
    from tpunet.utils.cache import enable_persistent_compile_cache
    enable_persistent_compile_cache()

    cfg = ServeConfig(
        host=args.host, port=args.port, slots=args.slots,
        queue_max=args.queue_max, prefill_buckets=buckets,
        paged_kv=args.paged_kv, kv_pages=args.kv_pages,
        kv_page_tokens=args.kv_page_tokens, kv_dtype=args.kv_dtype,
        device_sampling=args.device_sampling,
        prefix_cache=args.prefix_cache,
        prefix_cache_pages=args.prefix_cache_pages,
        prefix_store=args.prefix_store,
        default_max_new_tokens=args.max_new_tokens,
        max_new_tokens_cap=args.max_new_tokens_cap,
        default_deadline_s=args.deadline_s,
        classify_batch_max=args.classify_batch_max,
        classify_window_ms=args.classify_window_ms,
        emit_every_s=args.emit_every_s,
        drain_timeout_s=args.drain_timeout_s,
        run_id=args.run_id, aot_cache=args.aot_cache,
        chaos=args.chaos, trace_sample=args.trace_sample,
        spec_decode=args.spec_decode, spec_k=args.spec_k,
        spec_draft_width_mult=args.spec_draft_width_mult,
        spec_draft_checkpoint=args.spec_draft_checkpoint)
    model_cfg = ModelConfig(
        name=args.model, vit_hidden=args.vit_hidden,
        vit_depth=args.vit_depth, vit_heads=args.vit_heads,
        vocab_size=args.vocab_size, max_seq_len=args.max_seq_len,
        dropout_rate=0.0, moe_experts=args.moe_experts,
        moe_every=args.moe_every, moe_top_k=args.moe_top_k,
        moe_capacity_factor=args.moe_capacity_factor,
        pp_virtual=args.pp_virtual)
    mesh = None
    if args.mesh_model > 1:
        from tpunet.parallel import make_mesh
        mesh = make_mesh(MeshConfig(data=1, model=args.mesh_model))
    model, variables = load_lm(model_cfg,
                               checkpoint_dir=args.checkpoint_dir,
                               mesh=mesh, train_pipe=args.train_pipe)
    aot_store = None
    if cfg.aot_cache and mesh is None:
        from tpunet.serve.engine import build_aot_store
        aot_store = build_aot_store(cfg.aot_cache, model_cfg, cfg)
    prefix_store = None
    if cfg.prefix_store and cfg.prefix_cache and cfg.paged_kv:
        from tpunet.serve.prefixcache import build_prefix_store
        prefix_store = build_prefix_store(cfg.prefix_store, model_cfg,
                                          cfg)
    engine = Engine(model, variables, cfg, mesh=mesh,
                    aot_store=aot_store, prefix_store=prefix_store)
    if engine.aot_status:
        print(f"aot warm-start: {engine.aot_status}", flush=True)
    registry = engine.registry

    metrics_logger = None
    exporters = []
    metrics_dir = args.metrics_dir or args.checkpoint_dir
    # Black-box flight recorder for the SERVING process (README
    # "Crash forensics"): event ring + crash handlers + watcher into
    # <metrics-dir>/flightrec, so a dead replica leaves a
    # crash_report.json next to its metrics. Same default-ON as the
    # trainer; the engine/frontend record() calls land here.
    recorder = None
    if metrics_dir:
        from tpunet.obs import flightrec
        recorder = flightrec.install(metrics_dir, run_id=args.run_id)
    if metrics_dir:
        metrics_logger = MetricsLogger(metrics_dir, resume=True)
        registry.add_sink(JsonlSink(metrics_logger))
    if args.statsd or args.obs_http or args.obs_webhook:
        from tpunet.config import ExportConfig
        from tpunet.obs.export import build_exporters
        exporters = build_exporters(
            ExportConfig(statsd=args.statsd, http=args.obs_http,
                         webhook=args.obs_webhook),
            registry)
        for exporter in exporters:
            registry.add_sink(exporter)

    batcher = None
    if args.classifier_checkpoint_dir:
        from tpunet.infer.predict import Predictor
        pred = Predictor(
            model_cfg=ModelConfig(name=args.classifier_model,
                                  dropout_rate=0.0),
            data_cfg=DataConfig(image_size=args.classifier_image_size),
            checkpoint_dir=args.classifier_checkpoint_dir)
        batcher = ClassifyBatcher(pred,
                                  batch_max=cfg.classify_batch_max,
                                  window_ms=cfg.classify_window_ms,
                                  registry=registry)
    return ServeServer(engine, classify_batcher=batcher,
                       host=cfg.host, port=cfg.port,
                       metrics_logger=metrics_logger,
                       exporters=exporters, run_id=cfg.run_id,
                       flight_recorder=recorder)


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    server = build_server(args)
    server.start()
    print(f"tpunet.serve listening on "
          f"http://{args.host}:{server.port} "
          f"(slots={server.engine.slots}, "
          f"buckets={server.engine.buckets})", flush=True)

    import threading
    stop = threading.Event()

    def _term(signum, frame):
        print(f"signal {signum}: draining "
              f"(timeout {args.drain_timeout_s}s)...", flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop.is_set():
        stop.wait(0.5)
        if not server.engine.healthy:
            print(f"engine unhealthy: {server.engine.error}; "
                  "draining", file=sys.stderr, flush=True)
            stop.set()
    clean = server.drain(args.drain_timeout_s)
    print(f"drained ({'clean' if clean else 'forced'})", flush=True)
    return 0 if server.engine.error is None else 2


if __name__ == "__main__":
    sys.exit(main())
