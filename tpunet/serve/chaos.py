"""Deterministic fault injection for the SERVING tier.

Training got its chaos harness in ``tpunet/elastic/chaos.py``; this is
the serve/router twin — the tier that faces live clients. ``--chaos
SPEC`` on the serve CLI (or on the router CLI, scoped per replica
index and forwarded to spawned children) installs an injector whose
hooks the engine and the HTTP frontend call at the exact points real
faults strike: token production, prefill dispatch, health probes, and
the streaming relay.

Spec grammar (full reference in docs/serving.md "Mid-stream failover
& serve-tier chaos")::

    spec    := event (';' event)*
    event   := kind '@' where ('=' N)? (':' key '=' value)*

    kill@tokens=N                SIGKILL after this replica has
                                 generated its N-th token (counted
                                 across requests since boot) — the
                                 token reaches the stream first, so
                                 the seam where a replica "emitted
                                 token N as it died" is exercised
    kill@prefill[=K]             SIGKILL during the K-th prefill
                                 device call (default 1), before any
                                 response byte — the re-route-before-
                                 first-byte path
    stall@tokens=N:ms=M          once N tokens are generated, the
                                 engine loop AND every /healthz
                                 answer sleep M ms — the wedged
                                 replica the router must stall-evict
    drop-probe@prob=P:seed=X     seeded Bernoulli(P): matching
                                 /healthz probes answer 500 — flaky-
                                 probe resilience (same seed => same
                                 afflicted probes)
    slow-stream@ms=M             every streamed ndjson line is
                                 delayed M ms — slow-consumer /
                                 slow-producer relay behavior

On the ROUTER CLI every event additionally takes ``:replica=I`` to
scope it to spawned child ``I`` (``split_by_replica``); unscoped
events reach every child. Events are one-shot for ``kill``, standing
for the rest. Kills are real ``SIGKILL``s — no flush, no drain,
exactly what the failover journal must survive.

Everything here is host-side (never traced into jit — tpucheck R3).
"""

from __future__ import annotations

import os
import random
import signal
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpunet.obs import flightrec


class ServeChaosError(ValueError):
    """A ``--chaos`` spec that does not parse; the message quotes the
    offending event and the grammar form it missed."""


_KINDS = ("kill", "stall", "drop-probe", "slow-stream")
_WHERES = {
    "kill": ("tokens", "prefill"),
    "stall": ("tokens",),
    "drop-probe": ("prob",),
    "slow-stream": ("ms",),
}
_FLOAT_KEYS = ("ms", "prob")
_INT_KEYS = ("seed", "replica", "tokens", "prefill")


@dataclass
class _Event:
    kind: str
    where: str                 # tokens | prefill | prob | ms
    at: Optional[float]        # count / ordinal / probability / ms
    params: Dict[str, float] = field(default_factory=dict)
    fired: int = 0

    def param(self, key: str, default: float = 0.0) -> float:
        return self.params.get(key, default)

    def render(self) -> str:
        kv = "".join(f":{k}={v:g}"
                     for k, v in sorted(self.params.items()))
        at = "" if self.at is None else f"={self.at:g}"
        return f"{self.kind}@{self.where}{at}{kv}"


def _parse_event(text: str) -> _Event:
    def bad(why: str) -> ServeChaosError:
        return ServeChaosError(
            f"bad serve chaos event {text!r}: {why} (grammar: "
            f"kind@where=N[:key=value]*, kinds {'/'.join(_KINDS)} — "
            "see docs/serving.md)")

    head, _, tail = text.partition(":")
    if "@" not in head:
        raise bad("missing '@'")
    kind, _, where_part = head.partition("@")
    kind = kind.strip()
    if kind not in _KINDS:
        raise bad(f"unknown kind {kind!r}")
    where, _, at_text = where_part.partition("=")
    where = where.strip()
    if where not in _WHERES[kind]:
        raise bad(f"kind {kind!r} takes @{'/@'.join(_WHERES[kind])}, "
                  f"not @{where!r}")
    at: Optional[float] = None
    if at_text:
        try:
            at = float(at_text)
        except ValueError:
            raise bad(f"non-numeric position {at_text!r}") from None
    elif where != "prefill":
        raise bad(f"@{where} needs a value (e.g. @{where}=3)")
    params: Dict[str, float] = {}
    if tail:
        for pair in tail.split(":"):
            key, eq, val = pair.partition("=")
            key = key.strip()
            if not eq or key not in _FLOAT_KEYS + _INT_KEYS:
                raise bad(f"unknown or malformed key {pair!r}")
            try:
                params[key] = float(val)
            except ValueError:
                raise bad(f"non-numeric value in {pair!r}") from None
    if kind == "stall" and "ms" not in params:
        raise bad("stall needs :ms=MILLIS")
    if where == "prob":
        if at is None or not 0.0 < at <= 1.0:
            raise bad("prob must be in (0, 1]")
        if "seed" not in params:
            raise bad("drop-probe needs :seed=N (seeded => "
                      "reproducible)")
    return _Event(kind=kind, where=where, at=at, params=params)


def split_by_replica(spec: str) -> Dict[Optional[int], str]:
    """Split a router-level spec into per-child specs by the
    ``replica=I`` scope key: ``{0: "kill@tokens=5", None: "..."}``.
    ``None`` carries the unscoped events (they reach every child);
    the scope key itself is stripped from the forwarded event. The
    whole spec is parse-validated first so a typo fails the router
    boot, not a child boot minutes later."""
    out: Dict[Optional[int], List[str]] = {}
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        ev = _parse_event(part)          # raises ServeChaosError
        replica = ev.params.pop("replica", None)
        idx = None if replica is None else int(replica)
        out.setdefault(idx, []).append(ev.render())
    return {idx: ";".join(parts) for idx, parts in out.items()}


def spec_for_replica(spec: str, index: int) -> str:
    """The ``--chaos`` spec child ``index`` should be launched with
    (scoped events for this index + every unscoped event), or ""
    when nothing addresses it."""
    if not spec:
        return ""
    by_idx = split_by_replica(spec)
    parts = [s for key, s in by_idx.items()
             if key is None or key == index]
    return ";".join(parts)


class ServeChaos:
    """The installed injector: parsed events + the hooks the engine
    and HTTP frontend call. ``kill`` injection is synchronous on the
    calling thread (the engine loop / prefill path); ``stall`` flips
    a standing flag that both the engine loop and the health endpoint
    observe — a wedged replica is wedged everywhere the router can
    see it."""

    def __init__(self, events: List[_Event], *,
                 kill: Callable[[int, int], None] = os.kill,
                 sleep: Callable[[float], None] = time.sleep):
        self.events = events
        self._kill = kill
        self._sleep = sleep
        self._tokens = 0
        self._prefills = 0
        self._probes = 0
        self._rngs: Dict[int, random.Random] = {}
        self.stalled = False
        self.stall_ms = 0.0

    @classmethod
    def parse(cls, spec: str, *,
              kill: Callable[[int, int], None] = os.kill,
              sleep: Callable[[float], None] = time.sleep
              ) -> "ServeChaos":
        events = [_parse_event(part.strip())
                  for part in spec.split(";") if part.strip()]
        if not events:
            raise ServeChaosError(f"empty chaos spec {spec!r}")
        return cls(events, kill=kill, sleep=sleep)

    def _fire_kill(self, ev: _Event, what: str) -> None:
        ev.fired += 1
        # The breadcrumb goes into the crash-durable ring FIRST: the
        # post-mortem report then says the death was injected, not
        # organic.
        flightrec.record("chaos", f"SIGKILL injected ({what})")
        self._kill(os.getpid(), signal.SIGKILL)

    # -- engine hooks --------------------------------------------------

    def on_token(self) -> None:
        """Called by the engine after each generated token is pushed
        (the token reaches the stream BEFORE the kill — the seam a
        failover journal must survive)."""
        self._tokens += 1
        for ev in self.events:
            if ev.where != "tokens" or ev.at is None \
                    or self._tokens < int(ev.at):
                continue
            if ev.kind == "kill" and not ev.fired:
                self._fire_kill(ev, f"tokens={self._tokens}")
            elif ev.kind == "stall" and not self.stalled:
                self.stalled = True
                self.stall_ms = ev.param("ms")
                flightrec.record(
                    "chaos", f"stall armed tokens={self._tokens} "
                             f"ms={self.stall_ms:g}")

    def on_prefill(self) -> None:
        """Called by the engine before each prefill device call."""
        self._prefills += 1
        for ev in self.events:
            if ev.kind != "kill" or ev.where != "prefill" or ev.fired:
                continue
            ordinal = 1 if ev.at is None else int(ev.at)
            if self._prefills >= ordinal:
                self._fire_kill(ev, f"prefill={self._prefills}")

    def maybe_stall(self) -> None:
        """Engine-loop stall point: once armed, every iteration sleeps
        the configured budget (the decode stream wedges)."""
        if self.stalled:
            self._sleep(self.stall_ms / 1e3)

    # -- frontend hooks ------------------------------------------------

    def on_probe(self) -> bool:
        """Called per /healthz request. True = drop this probe (the
        handler answers 500). A standing stall also wedges the probe
        itself (sleep past the router's probe timeout) so the wedged
        replica fails its health checks the way a wedged process
        does."""
        if self.stalled:
            self._sleep(self.stall_ms / 1e3)
        self._probes += 1
        for i, ev in enumerate(self.events):
            if ev.kind != "drop-probe":
                continue
            rng = self._rngs.setdefault(
                i, random.Random(int(ev.param("seed"))))
            # One draw per probe keeps the sequence probe-addressed:
            # the same seed drops the same probes in every run.
            if rng.random() < float(ev.at or 0.0):
                ev.fired += 1
                flightrec.record("chaos",
                                 f"probe dropped n={self._probes}")
                return True
        return False

    def on_stream_line(self) -> None:
        """Called by the streaming frontend before each relayed ndjson
        line (slow-stream)."""
        for ev in self.events:
            if ev.kind == "slow-stream" and ev.at:
                ev.fired += 1
                self._sleep(float(ev.at) / 1e3)

    def render(self) -> str:
        return ";".join(ev.render() for ev in self.events)


def install(spec: str) -> Optional[ServeChaos]:
    """Parse and arm an injector for this serve process (``--chaos``),
    or None for an empty spec."""
    if not spec:
        return None
    chaos = ServeChaos.parse(spec)
    flightrec.record("chaos", f"armed {chaos.render()}")
    return chaos
