"""Micro-batched classifier path: coalesce concurrent /v1/classify
requests into one jitted batched forward.

The parity ``Predictor`` (tpunet/infer/predict.py) jits a
single-image forward — correct, but a thread-per-request server then
pays one full model dispatch per image. Here concurrent requests are
held for at most ``classify_window_ms`` and run as ONE batched forward
padded to a fixed ``classify_batch_max`` — a single compiled program
for the MODEL forward (the expensive part) regardless of arrival
pattern: padding rows are zero images whose outputs are dropped.
Preprocessing (resize + normalize) runs per-image on the handler
thread via eager ``jax.image.resize`` — the Predictor's exact
transform, which specializes per input image shape exactly like the
parity Predictor's jitted forward does (that per-novel-shape compile
is the price of bit-matching its antialiased downscale; clients with
a fixed camera/image size pay it once).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import numpy as np


class _Pending:
    __slots__ = ("image", "event", "probs", "error")

    def __init__(self, image: np.ndarray):
        self.image = image
        self.event = threading.Event()
        self.probs: Optional[np.ndarray] = None
        self.error: Optional[str] = None


class ClassifyBatcher:
    """Wraps a ``Predictor`` with a batching window.

    ``submit(image)`` blocks the CALLING (HTTP handler) thread until
    its probs are ready; the single worker thread owns the device.
    """

    def __init__(self, predictor, *, batch_max: int = 8,
                 window_ms: float = 2.0, registry=None):
        import jax
        import jax.numpy as jnp

        from tpunet.obs.registry import Registry

        if batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {batch_max}")
        self.predictor = predictor
        self.batch_max = int(batch_max)
        self.window_s = float(window_ms) / 1000.0
        self.registry = registry if registry is not None else Registry()
        self._q: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        size = predictor.data_cfg.image_size
        self._size = size
        self._mean = np.asarray(predictor.data_cfg.mean, np.float32)
        self._std = np.asarray(predictor.data_cfg.std, np.float32)

        def forward(variables, batch):
            logits = predictor.model.apply(variables, batch, train=False)
            return jax.nn.softmax(logits, axis=-1)

        self._forward = jax.jit(forward)
        self._jnp = jnp
        # Host-thread registry (tpunet/obs/flightrec/): a batched
        # forward wedged on the device past the budget pages
        # thread_stalled; idle queue waits do not (tpucheck R4).
        from tpunet.obs import flightrec
        self._thread_handle = flightrec.register_thread(
            "serve-classify", stall_after_s=120.0)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpunet-serve-classify")
        self._thread.start()

    @property
    def healthy(self) -> bool:
        return self._thread.is_alive()

    def _preprocess(self, image: np.ndarray) -> np.ndarray:
        """The Predictor's serve-time transform (uint8 HWC in,
        normalized float32 SxS out) — one constant everywhere, so the
        batched path cannot re-introduce the reference's train/serve
        normalization skew."""
        import jax
        x = image.astype(np.float32) / 255.0
        x = np.asarray(jax.image.resize(
            x, (self._size, self._size, 3), method="bilinear"))
        return (x - self._mean) / self._std

    def submit(self, image, timeout: float = 30.0) -> np.ndarray:
        """Classify one image (uint8 HWC array or PIL); returns class
        probabilities. Blocks until the batched forward that includes
        this image completes."""
        if hasattr(image, "convert"):
            image = np.asarray(image.convert("RGB"))
        image = np.asarray(image)
        if image.dtype != np.uint8:
            image = np.clip(image * 255 if image.max() <= 1.0 else image,
                            0, 255).astype(np.uint8)
        item = _Pending(self._preprocess(image))
        self._q.put(item)
        if not item.event.wait(timeout):
            raise TimeoutError("classify batch did not complete "
                               f"within {timeout}s")
        if item.error is not None:
            raise RuntimeError(item.error)
        return item.probs

    def _run(self) -> None:
        reg = self.registry
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            deadline = time.perf_counter() + self.window_s
            while len(batch) < self.batch_max:
                left = deadline - time.perf_counter()
                if left <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=left))
                except queue.Empty:
                    break
            t0 = time.perf_counter()
            self._thread_handle.beat("busy")
            try:
                x = np.zeros((self.batch_max, self._size, self._size, 3),
                             np.float32)
                for i, item in enumerate(batch):
                    x[i] = item.image
                probs = np.asarray(self._forward(
                    self.predictor.variables, self._jnp.asarray(x)))
                for i, item in enumerate(batch):
                    item.probs = probs[i]
                    item.event.set()
            except Exception as e:  # noqa: BLE001 — fail the batch, not
                # the worker: the next window must still serve.
                for item in batch:
                    item.error = f"{type(e).__name__}: {e}"
                    item.event.set()
            self._thread_handle.beat("idle")
            reg.counter("serve_classify_requests_total").inc(len(batch))
            reg.counter("serve_classify_batches_total").inc()
            reg.histogram("serve_classify_batch_size").observe(len(batch))
            reg.histogram("serve_classify_s").observe(
                time.perf_counter() - t0)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
