"""Continuous-batching decode engine over a fixed KV-slot pool.

One jitted masked decode step is compiled ONCE for the pool batch
``[slots, 1]`` and amortized across every in-flight request: each
iteration feeds every active slot its next token at its own position
(per-row positions + active mask, tpunet/models/vit.py
``Attention._decode_attend``), so requests join mid-flight and finished
ones free their slot without any recompilation. Prefill runs through
the same masked path as a chunked multi-token call, padded to one of a
fixed set of length buckets — the total compile count is bounded at
``1 + len(prefill_buckets)`` programs for the life of the server.

Sampling is host-side (per-request temperature/top-k/top-p/seed differ
across a batch, and argmax on host equals argmax on device), mirroring
``models.lm.filter_logits`` semantics: top-k first, then the nucleus
over the renormalized post-top-k distribution. Greedy output is
token-identical to ``models.lm.generate`` (engine parity test).

Obs wiring: SLO counters/gauges/histograms land in a ``tpunet.obs``
``Registry`` (serve_* names, docs/metrics_schema.md ``obs_serve``),
prefill/decode phases run under trace spans, and a periodic
``obs_serve`` record is emitted to every attached sink/exporter.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional

import numpy as np

from tpunet.serve.scheduler import (FINISH_CANCELLED, FINISH_DEADLINE,
                                    FINISH_DRAIN, FINISH_ERROR,
                                    FINISH_LENGTH, FINISH_STOP,
                                    GenerateRequest, RequestQueue)


class PromptTooLongError(Exception):
    """Prompt exceeds the largest prefill bucket or the KV length."""


@contextlib.contextmanager
def _ring_span(name: str):
    """The serve twin of the trainer's ``_RecordedSpan``: an xprof
    trace span whose begin/end ALSO land in the flight-recorder ring
    (the unified timeline's device phases; the crash tail's "which
    phase was the replica in"). ``span_end`` sits in a finally so a
    raising device call cannot leave a dangling open span for the
    timeline to stretch to the end of the recording."""
    from tpunet.obs import flightrec
    from tpunet.obs.spans import span
    flightrec.record("span", name)
    try:
        with span(name):
            yield
    finally:
        flightrec.record("span_end", name)


def sample_token(logits: np.ndarray, req: GenerateRequest) -> int:
    """Host-side next-token choice from one row of logits [V].

    Greedy (temperature <= 0) is exact argmax. Sampling mirrors
    ``models.lm.filter_logits``: top-k truncation first, then nucleus
    over the renormalized post-top-k distribution; the draw uses the
    request's own seeded numpy Generator (deterministic per request,
    independent across slots).
    """
    if req.temperature <= 0:
        return int(np.argmax(logits))
    lg = logits.astype(np.float64) / req.temperature
    v = lg.shape[-1]
    if req.top_k > 0 and req.top_k < v:
        kth = np.sort(lg)[-req.top_k]
        lg = np.where(lg >= kth, lg, -np.inf)
    if 0.0 < req.top_p < 1.0:
        srt = np.sort(lg)[::-1]
        probs = np.exp(srt - srt.max())
        probs /= probs.sum()
        keep = np.cumsum(probs) - probs < req.top_p
        cutoff = srt[keep].min()
        lg = np.where(lg >= cutoff, lg, -np.inf)
    lg -= lg.max()
    p = np.exp(lg)
    p /= p.sum()
    return int(req.rng().choice(v, p=p))


def build_serve_record(reg, *, queue_depth: int, active_slots: int,
                       slots: int, uptime_s: float, window_s: float,
                       final: bool = False) -> dict:
    """The ``obs_serve`` record body (docs/metrics_schema.md):
    cumulative counters + window histogram summaries. Module-level so
    the schema-conformance check can exercise the exact record shape
    without standing up an engine; the TTFT/e2e histograms also export
    their bounded window sample — the fleet aggregator merges replica
    SLO percentiles from sample points, not from per-replica p99s."""
    record = {
        "uptime_s": round(uptime_s, 3),
        "window_s": round(window_s, 3),
        "queue_depth": queue_depth,
        "active_slots": active_slots,
        "slots": slots,
        "requests_total": int(
            reg.counter("serve_requests_total").value),
        "requests_completed": int(
            reg.counter("serve_requests_completed").value),
        "requests_rejected": int(
            reg.counter("serve_requests_rejected").value),
        "tokens_total": int(reg.counter("serve_tokens_total").value),
        "decode_steps_total": int(
            reg.counter("serve_decode_steps_total").value),
        "prefills_total": int(
            reg.counter("serve_prefills_total").value),
    }
    for name, key in (("serve_ttft_s", "ttft"),
                      ("serve_token_s", "token_latency"),
                      ("serve_e2e_s", "e2e"),
                      ("serve_prefill_s", "prefill")):
        hist = reg.histogram(name)
        summ = hist.summary()
        for stat in ("p50", "p90", "p99", "mean", "count"):
            if stat in summ:
                record[f"{key}_{stat}_s" if stat != "count"
                       else f"{key}_count"] = (
                    round(summ[stat], 6) if stat != "count"
                    else int(summ[stat]))
        if key in ("ttft", "e2e") and summ:
            record[f"{key}_sample"] = [
                round(v, 6) for v in hist.export_sample()]
            if summ.get("approx"):
                record[f"{key}_approx"] = 1
    if final:
        record["final"] = True
    return record


def build_aot_store(directory: str, model_cfg, serve_cfg):
    """The engine's ``AotProgramStore`` (tpunet/utils/cache.py), keyed
    by every config field that selects a compiled program: the model
    architecture plus the pool shape. A replica booted with a different
    width/depth/slots gets a clean store MISS, never a wrong program
    (the store key additionally folds in jax version + device kind)."""
    import dataclasses

    from tpunet.utils.cache import AotProgramStore

    digest = AotProgramStore.digest({
        "model": dataclasses.asdict(model_cfg),
        "slots": serve_cfg.slots,
        "prefill_buckets": list(serve_cfg.prefill_buckets),
    })
    return AotProgramStore(directory, digest)


class _Slot:
    """Host-side bookkeeping for one KV-cache row."""

    __slots__ = ("req", "pos", "next_token", "generated")

    def __init__(self, req: GenerateRequest, pos: int, next_token: int):
        self.req = req
        self.pos = pos            # next cache write position
        self.next_token = next_token
        self.generated = 1        # first token came from prefill


class Engine:
    """Slot-pool continuous-batching engine for one LM.

    ``model``/``variables`` come from ``infer.generate.load_lm`` (pass
    the same ``mesh`` for tensor-parallel serving — the KV pool is then
    created sharded over the mesh 'model' axis to match the attention's
    head-sharded writes). The engine owns a single background thread;
    ``submit`` is thread-safe and non-blocking (bounded queue).
    """

    def __init__(self, model, variables, cfg, *, registry=None,
                 mesh=None, aot_store=None):
        import jax
        import jax.numpy as jnp

        from tpunet.obs.registry import Registry

        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.mesh = mesh
        self.registry = registry if registry is not None else Registry()
        self.max_seq_len = int(model.max_len)
        self.slots = int(cfg.slots)
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {cfg.slots}")
        self.buckets = tuple(sorted(
            b for b in cfg.prefill_buckets if b <= self.max_seq_len))
        if not self.buckets:
            self.buckets = (self.max_seq_len,)
        self.queue = RequestQueue(cfg.queue_max,
                                  on_finish=self._account_finish)
        self._active: List[Optional[_Slot]] = [None] * self.slots
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drain_kill = threading.Event()
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_handle = None       # flightrec registry handle
        self.error: Optional[str] = None
        self._last_emit = time.perf_counter()
        self._started = time.perf_counter()

        # -- device programs (compiled lazily, one per shape) ----------
        def _masked_step(params, cache, tokens, positions, active):
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tokens, train=False,
                decode=True, pos_offset=positions, decode_active=active,
                mutable=["cache"])
            return mutated["cache"], logits

        # One callable; jit specializes per token shape: [N, 1] decode
        # plus one [N, Lb] program per prefill bucket. The cache is
        # donated — it is the engine's single biggest buffer and every
        # call replaces it.
        self._step = jax.jit(_masked_step, donate_argnums=(1,))
        self._cache = self._make_cache()
        self._inactive_tok = np.zeros((self.slots, 1), np.int32)
        # AOT warm-start (tpunet/utils/cache.py AotProgramStore): the
        # engine's program set is closed — [N, 1] decode + one [N, Lb]
        # per bucket — so fully-compiled executables deserialize at
        # boot and the jit path above becomes the fallback for shapes
        # the store has never seen. Single-device only: a sharded pool
        # would bake device assignments into the executable.
        self._aot: dict = {}
        self.aot_status: dict = {}
        if aot_store is not None and mesh is None:
            self._warm_start_aot(aot_store)

    def _warm_start_aot(self, store) -> None:
        """Load (or compile-and-save) every program the pool can run.
        Deserialization skips tracing/lowering/XLA entirely — the
        compile-bound replica cold-start becomes an mmap + relink."""
        import jax

        def sds(tree):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

        params_s = sds(self.variables["params"])
        cache_s = sds(self._cache)
        pos_s = jax.ShapeDtypeStruct((self.slots,), np.int32)
        act_s = jax.ShapeDtypeStruct((self.slots,), bool)
        for width in (1,) + self.buckets:
            tag = f"w{width}"
            toks_s = jax.ShapeDtypeStruct((self.slots, width), np.int32)
            program = store.load("masked_step", tag)
            if program is None:
                program = self._step.lower(
                    params_s, cache_s, toks_s, pos_s, act_s).compile()
                saved = store.save("masked_step", tag, program)
                self.aot_status[tag] = ("compiled+saved" if saved
                                        else "compiled")
            else:
                self.aot_status[tag] = "loaded"
            self._aot[width] = program

    def _dispatch_step(self, toks, positions, active):
        """Run one masked-step program: the AOT executable for this
        token width when warm-started, the jit fallback otherwise."""
        program = self._aot.get(toks.shape[1])
        if program is None:
            program = self._step
        return program(self.variables["params"], self._cache, toks,
                       positions, active)

    # -- pool construction ---------------------------------------------

    def _make_cache(self):
        import jax
        import jax.numpy as jnp
        shapes = jax.eval_shape(
            lambda: self.model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((self.slots, self.max_seq_len), jnp.int32),
                decode=True))

        def zeros(s):
            if self.mesh is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                tp = self.mesh.shape.get("model", 1)
                spec = (P(None, None, "model", None)
                        if (s.ndim == 4 and tp > 1
                            and s.shape[2] % tp == 0) else P())
                return jnp.zeros(s.shape, s.dtype,
                                 device=NamedSharding(self.mesh, spec))
            return jnp.zeros(s.shape, s.dtype)

        return jax.tree_util.tree_map(zeros, shapes["cache"])

    # -- public API ------------------------------------------------------

    def start(self) -> "Engine":
        # Host-thread registry (tpunet/obs/flightrec/): a decode
        # iteration wedged on the device past the budget pages
        # thread_stalled; idle waits (empty pool) do not.
        from tpunet.obs import flightrec
        self._thread_handle = flightrec.register_thread(
            "serve-engine", stall_after_s=120.0)
        flightrec.record("serve", f"engine start slots={self.slots}")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpunet-serve-engine")
        self._thread.start()
        return self

    @property
    def healthy(self) -> bool:
        return (self.error is None and self._thread is not None
                and self._thread.is_alive())

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def active_slots(self) -> int:
        return sum(1 for s in self._active if s is not None)

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLongError(
            f"prompt of {prompt_len} tokens exceeds the largest "
            f"prefill bucket ({self.buckets[-1]})")

    def submit(self, prompt, **kw) -> GenerateRequest:
        """Admit a request (or raise QueueFullError / DrainingError /
        PromptTooLongError / ValueError). Clamps max_new_tokens to the
        KV length; never blocks."""
        if self.error is not None:
            from tpunet.serve.scheduler import DrainingError
            raise DrainingError(f"engine failed: {self.error}")
        kw.setdefault("max_new_tokens", self.cfg.default_max_new_tokens)
        kw["max_new_tokens"] = min(int(kw["max_new_tokens"]),
                                   self.cfg.max_new_tokens_cap)
        if (kw.get("deadline_s") or 0) <= 0 \
                and self.cfg.default_deadline_s > 0:
            kw["deadline_s"] = self.cfg.default_deadline_s
        req = GenerateRequest(prompt, **kw)
        try:
            n = int(req.prompt.size)
            self.bucket_for(n)  # raises PromptTooLongError
            if n + req.max_new_tokens > self.max_seq_len:
                req.max_new_tokens = self.max_seq_len - n
                if req.max_new_tokens < 1:
                    raise PromptTooLongError(
                        f"prompt of {n} tokens leaves no room to "
                        f"generate (max_seq_len {self.max_seq_len})")
            self.queue.submit(req)       # may raise QueueFull/Draining
        except Exception:
            self.registry.counter("serve_requests_rejected").inc()
            raise
        # Request-lifecycle breadcrumb into the flight-recorder ring:
        # submit -> prefill -> first_token -> finish become the
        # queue/prefill/decode phases on the unified timeline
        # (tpunet/obs/history/timeline.py). ~1-2 us each, no-op
        # without an armed recorder.
        from tpunet.obs import flightrec
        flightrec.record("req", f"submit {req.id} len={req.prompt.size}")
        self.registry.counter("serve_requests_total").inc()
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())
        self._wake.set()
        return req

    def _kill_survivors(self, reason: str) -> None:
        """Finish every in-flight and still-queued request with
        ``reason``, through the shared accounting. Only safe from the
        engine thread, or once it can no longer run."""
        for i, slot in enumerate(self._active):
            if slot is not None:
                self._finish_slot(i, reason)
        while True:
            reqs = self.queue.pop_ready(self.queue.queue_max)
            if not reqs:
                break
            for req in reqs:
                req.finish(reason)
                self._account_finish(req, reason)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting, let in-flight (and
        already-queued) requests finish, then stop the loop. Returns
        True when everything finished inside the timeout; leftovers are
        cancelled with finish_reason='drain'."""
        self._draining.set()
        waiting = self.queue.close()
        self._wake.set()
        if self._thread is None or not self._thread.is_alive():
            # Never started (or already dead): there is no loop to
            # finish the work — fail fast instead of waiting a budget
            # that can never be met.
            clean = self.active_slots() == 0 and not waiting
            self._kill_survivors(FINISH_DRAIN)
            self._stop.set()
            self._drained.set()
            return clean
        budget = timeout if timeout is not None \
            else self.cfg.drain_timeout_s
        clean = self._drained.wait(budget)
        if not clean:
            # Timeout: the ENGINE finishes survivors (in-flight and
            # still-queued alike) with reason 'drain' — through
            # _finish_slot so the serve_finished_drain counters and
            # e2e accounting stay truthful, and distinguishable from a
            # client-initiated cancel.
            self._drain_kill.set()
            self._wake.set()
            self._drained.wait(5.0)
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return clean

    def stop(self) -> None:
        """Hard stop (tests / error paths): cancel everything. Unlike
        cancel() alone, every in-flight request is FINISHED here —
        clients blocked in result()/events() must unblock now, not at
        their own timeout."""
        self._draining.set()
        self.queue.fail_all("engine stopped")
        for slot in list(self._active):
            if slot is not None:
                slot.req.cancel()
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        # The loop exits at the top of its while without a final reap:
        # finish whatever it left behind (thread joined or never ran,
        # so this is single-threaded now).
        self._kill_survivors(FINISH_CANCELLED)

    # -- engine loop -----------------------------------------------------

    def _run(self) -> None:
        from tpunet.obs import flightrec
        handle = self._thread_handle
        try:
            while not self._stop.is_set():
                # Claim busy only when there is (potential) work: an
                # empty iteration is a poll, not work, and marking it
                # busy would (a) lie to the thread_stalled watchdog
                # and (b) flood the flight-recorder ring with ~100
                # busy/idle transition events per second from an idle
                # server, evicting the request breadcrumbs the
                # timeline exporter needs. A wedged device call always
                # had work, so stall detection is unaffected.
                if (self.active_slots() or self.queue.depth()
                        or self._drain_kill.is_set()):
                    handle.beat("busy")
                else:
                    handle.beat("idle")
                did_work = self._iterate()
                if self._draining.is_set() and self.active_slots() == 0 \
                        and self.queue.depth() == 0:
                    break
                if not did_work:
                    handle.beat("idle")
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
            handle.beat("idle")
            self._emit_record(final=True)
        except BaseException as e:  # noqa: BLE001 — engine death is a
            # liveness event: surface through /healthz and fail every
            # request fast rather than hanging clients.
            self.error = f"{type(e).__name__}: {e}"
            flightrec.record("serve", f"engine error: {e}")
            for slot in self._active:
                if slot is not None:
                    slot.req.finish(FINISH_ERROR, error=self.error)
            self._active = [None] * self.slots
            self.queue.fail_all(self.error)
        finally:
            self._drained.set()

    def _iterate(self) -> bool:
        """One engine iteration: reap -> admit(prefill) -> decode.
        Returns False when there was nothing to do (caller sleeps)."""
        if self._drain_kill.is_set():
            # Drain timeout expired: everything still alive finishes
            # with reason 'drain' (the shutdown took it, not a client).
            self._kill_survivors(FINISH_DRAIN)
            return False
        self._reap()
        admitted = self._admit()
        stepped = self._decode_iteration()
        now = time.perf_counter()
        if self.cfg.emit_every_s > 0 \
                and now - self._last_emit >= self.cfg.emit_every_s:
            self._emit_record()
        return admitted or stepped

    def _reap(self) -> None:
        """Free slots whose request was cancelled or hit its deadline
        (cooperative cancellation point)."""
        now = time.perf_counter()
        for i, slot in enumerate(self._active):
            if slot is None:
                continue
            if slot.req.cancelled:
                self._finish_slot(i, FINISH_CANCELLED)
            elif slot.req.expired(now):
                self._finish_slot(i, FINISH_DEADLINE)

    def _account_finish(self, req, reason: str) -> None:
        """Finish accounting shared by slot-finishes and requests the
        QUEUE finishes before they ever reach a slot: the counters must
        reconcile (requests_total == rejected + sum(finished_*))."""
        reg = self.registry
        from tpunet.obs import flightrec
        flightrec.record("req", f"finish {req.id} {reason}")
        reg.counter(f"serve_finished_{reason}").inc()
        if reason in (FINISH_LENGTH, FINISH_STOP):
            reg.counter("serve_requests_completed").inc()
        if req.e2e_s is not None:
            reg.histogram("serve_e2e_s").observe(req.e2e_s)

    def _finish_slot(self, i: int, reason: str) -> None:
        slot = self._active[i]
        self._active[i] = None
        slot.req.finish(reason)
        self._account_finish(slot.req, reason)
        self.registry.gauge("serve_active_slots").set(self.active_slots())

    def _admit(self) -> bool:
        """Admit waiting requests into free slots and prefill them,
        grouped by bucket so each group is one device call."""
        free = [i for i, s in enumerate(self._active) if s is None]
        if not free:
            return False
        reqs = self.queue.pop_ready(len(free))
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())
        if not reqs:
            return False
        if self._thread_handle is not None:
            # A request can land between the top-of-loop idle beat and
            # this pop; mark busy BEFORE the prefill device call, or a
            # wedged call would hang an officially-idle thread and the
            # thread_stalled watchdog would never fire.
            self._thread_handle.beat("busy")
        by_bucket = {}
        for req, slot_i in zip(reqs, free):
            by_bucket.setdefault(self.bucket_for(req.prompt.size),
                                 []).append((slot_i, req))
        for bucket, group in sorted(by_bucket.items()):
            self._prefill(bucket, group)
        self.registry.gauge("serve_active_slots").set(self.active_slots())
        return True

    def _prefill(self, bucket: int, group) -> None:
        """One chunked-prefill device call for every admitted request
        padded to this bucket; K/V land in each slot's cache row and
        the first token is sampled from the last REAL prompt position.
        The padded tail writes garbage K/V beyond the prompt — masked
        invariant: a decode query at position p attends only j <= p and
        overwrites position p first, so padding is never visible."""
        t0 = time.perf_counter()
        toks = np.zeros((self.slots, bucket), np.int32)
        active = np.zeros((self.slots,), bool)
        for slot_i, req in group:
            toks[slot_i, :req.prompt.size] = req.prompt
            active[slot_i] = True
            # Slot the request BEFORE the device call: if the step
            # raises, the engine's failure handler finds (and fails)
            # it in _active instead of stranding a popped request.
            self._active[slot_i] = _Slot(req, pos=req.prompt.size,
                                         next_token=0)
        positions = np.zeros((self.slots,), np.int32)
        from tpunet.obs import flightrec
        for _, req in group:
            flightrec.record("req", f"prefill {req.id}")
        with _ring_span("tpunet/serve_prefill"):
            self._cache, logits = self._dispatch_step(toks, positions,
                                                      active)
            logits = np.asarray(logits)
        reg = self.registry
        for slot_i, req in group:
            n = req.prompt.size
            first = sample_token(logits[slot_i, n - 1], req)
            self._active[slot_i].next_token = first
            req.push_token(first)
            flightrec.record("req", f"first_token {req.id}")
            reg.counter("serve_tokens_total").inc()
            reg.histogram("serve_ttft_s").observe(req.ttft_s)
            self._slot_maybe_finish(slot_i, first)
        reg.counter("serve_prefills_total").inc()
        reg.counter("serve_prefill_tokens_total").inc(
            sum(r.prompt.size for _, r in group))
        reg.histogram("serve_prefill_s").observe(
            time.perf_counter() - t0)

    def _slot_maybe_finish(self, slot_i: int, token: int) -> bool:
        """Stop checks after a sampled token; True when the slot was
        freed."""
        slot = self._active[slot_i]
        req = slot.req
        if req.stop_token is not None and token == req.stop_token:
            self._finish_slot(slot_i, FINISH_STOP)
            return True
        if slot.generated >= req.max_new_tokens \
                or slot.pos + 1 > self.max_seq_len:
            self._finish_slot(slot_i, FINISH_LENGTH)
            return True
        return False

    def _decode_iteration(self) -> bool:
        """One masked decode step across the whole pool: every active
        slot consumes its pending token at its own position and samples
        the next one."""
        live = [(i, s) for i, s in enumerate(self._active)
                if s is not None]
        if not live:
            return False
        t0 = time.perf_counter()
        toks = self._inactive_tok.copy()
        positions = np.zeros((self.slots,), np.int32)
        active = np.zeros((self.slots,), bool)
        for i, slot in live:
            toks[i, 0] = slot.next_token
            positions[i] = slot.pos
            active[i] = True
        with _ring_span("tpunet/serve_decode"):
            self._cache, logits = self._dispatch_step(toks, positions,
                                                      active)
            logits = np.asarray(logits)
        lap = time.perf_counter() - t0
        reg = self.registry
        reg.counter("serve_decode_steps_total").inc()
        reg.histogram("serve_decode_iter_s").observe(lap)
        # per-token latency: the iteration produced one token for each
        # live slot, each of which waited the full iteration.
        reg.histogram("serve_token_s").observe(lap)
        for i, slot in live:
            nxt = sample_token(logits[i, 0], slot.req)
            slot.pos += 1
            slot.next_token = nxt
            slot.generated += 1
            slot.req.push_token(nxt)
            reg.counter("serve_tokens_total").inc()
            self._slot_maybe_finish(i, nxt)
        return True

    # -- obs -------------------------------------------------------------

    def _emit_record(self, final: bool = False) -> None:
        """One ``obs_serve`` record (docs/metrics_schema.md) per window:
        cumulative counters + window histograms, then a fresh window."""
        reg = self.registry
        now = time.perf_counter()
        window = now - self._last_emit
        self._last_emit = now
        record = build_serve_record(
            reg, queue_depth=self.queue.depth(),
            active_slots=self.active_slots(), slots=self.slots,
            uptime_s=now - self._started, window_s=window, final=final)
        # Host-thread gauges ride the serve registry too: GET /metrics
        # and exporters see thread_* ages for the engine loop and any
        # exporter drains.
        from tpunet.obs.flightrec.threads import THREADS
        THREADS.export_gauges(reg)
        reg.emit("obs_serve", record)
        reg.reset_window()
