"""Continuous-batching decode engine over a paged KV pool.

One jitted masked decode step is compiled ONCE for the pool batch
``[slots, 1]`` and amortized across every in-flight request: each
iteration feeds every active slot its next token at its own position
(per-row positions + active mask, tpunet/models/vit.py
``Attention._decode_attend``), so requests join mid-flight and finished
ones free their slot without any recompilation. Prefill runs through
the same masked path as a chunked multi-token call, padded to one of a
fixed set of length buckets — the total compile count is bounded at
``1 + len(prefill_buckets)`` programs for the life of the server.

KV memory is PAGED by default (``ServeConfig.paged_kv``;
``--no-paged-kv`` keeps the dense pool): per layer, K/V live in a
shared pool of ``kv_pages`` pages of ``kv_page_tokens`` tokens each,
addressed through per-slot page tables the engine owns host-side. A
slot costs HBM proportional to its prompt+generated length instead of
``max_seq_len`` — pages are allocated on advance, freed on finish, and
recycled; when the pool is exhausted the YOUNGEST blocked slot is
preempted back to the queue (its progress is kept and resumed by
re-prefilling prompt+generated, token streams never restart). int8
page payloads (``kv_dtype``, per page-row scale, eval-parity-gated)
halve the bf16 page cost again.

Prefix KV cache (``ServeConfig.prefix_cache``, on by default with
paging; tpunet/serve/prefixcache/): finished prefill pages become
immutable, content-addressed, refcounted objects inside the SAME
pool. Admission pins the longest cached page-aligned prefix into the
new slot's page table (zero prefill compute for those tokens),
re-prefills only the suffix, and copy-on-writes at the divergence
page when the full prefix is cached; release unpins, pool pressure
LRU-evicts. With ``--prefix-store`` the pages spill to a shared
filesystem (fsatomic first-writer-wins) and a respawned replica warms
from the fleet's prefix set at boot.

Sampling is DEVICE-side by default (``ServeConfig.device_sampling``):
one ``[slots]``-wide batched temperature/top-k/top-p step
(tpunet/serve/sampling.py, per-slot PRNG keys folded per step) is
fused onto the decode program, so only sampled int32 tokens cross the
host boundary — the per-slot host loop (and the ``[slots, V]`` logits
transfer feeding it) leaves the token path. ``sample_token`` below is
the surviving host-side parity reference (and the
``--no-device-sampling`` fallback); greedy output is token-identical
to ``models.lm.generate`` through either sampler (engine parity test).

Obs wiring: SLO counters/gauges/histograms land in a ``tpunet.obs``
``Registry`` (serve_* names incl. the ``serve_kv_*`` page-pool
gauges, docs/metrics_schema.md ``obs_serve``), prefill/decode phases
run under trace spans, and a periodic ``obs_serve`` record is emitted
to every attached sink/exporter.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional

import numpy as np

from tpunet.obs import tracing
from tpunet.serve.scheduler import (FINISH_CANCELLED, FINISH_DEADLINE,
                                    FINISH_DRAIN, FINISH_ERROR,
                                    FINISH_LENGTH, FINISH_STOP,
                                    GenerateRequest, RequestQueue)


class PromptTooLongError(Exception):
    """Prompt exceeds the largest prefill bucket or the KV length."""


@contextlib.contextmanager
def _ring_span(name: str):
    """The serve twin of the trainer's ``_RecordedSpan``: an xprof
    trace span whose begin/end ALSO land in the flight-recorder ring
    (the unified timeline's device phases; the crash tail's "which
    phase was the replica in"). ``span_end`` sits in a finally so a
    raising device call cannot leave a dangling open span for the
    timeline to stretch to the end of the recording."""
    from tpunet.obs import flightrec
    from tpunet.obs.spans import span
    flightrec.record("span", name)
    try:
        with span(name):
            yield
    finally:
        flightrec.record("span_end", name)


def sample_token(logits: np.ndarray, req: GenerateRequest) -> int:
    """Host-side next-token choice from one row of logits [V].

    Greedy (temperature <= 0) is exact argmax. Sampling mirrors
    ``models.lm.filter_logits``: top-k truncation first, then nucleus
    over the renormalized post-top-k distribution; the draw uses the
    request's own seeded numpy Generator (deterministic per request,
    independent across slots).
    """
    if req.temperature <= 0:
        return int(np.argmax(logits))
    lg = logits.astype(np.float64) / req.temperature
    v = lg.shape[-1]
    if req.top_k > 0 and req.top_k < v:
        kth = np.sort(lg)[-req.top_k]
        lg = np.where(lg >= kth, lg, -np.inf)
    if 0.0 < req.top_p < 1.0:
        srt = np.sort(lg)[::-1]
        probs = np.exp(srt - srt.max())
        probs /= probs.sum()
        keep = np.cumsum(probs) - probs < req.top_p
        cutoff = srt[keep].min()
        lg = np.where(lg >= cutoff, lg, -np.inf)
    lg -= lg.max()
    p = np.exp(lg)
    p /= p.sum()
    return int(req.rng().choice(v, p=p))


def build_serve_record(reg, *, queue_depth: int, active_slots: int,
                       slots: int, uptime_s: float, window_s: float,
                       final: bool = False) -> dict:
    """The ``obs_serve`` record body (docs/metrics_schema.md):
    cumulative counters + window histogram summaries. Module-level so
    the schema-conformance check can exercise the exact record shape
    without standing up an engine; the TTFT/e2e histograms also export
    their bounded window sample — the fleet aggregator merges replica
    SLO percentiles from sample points, not from per-replica p99s."""
    record = {
        "uptime_s": round(uptime_s, 3),
        "window_s": round(window_s, 3),
        "queue_depth": queue_depth,
        "active_slots": active_slots,
        "slots": slots,
        "requests_total": int(
            reg.counter("serve_requests_total").value),
        "requests_completed": int(
            reg.counter("serve_requests_completed").value),
        "requests_rejected": int(
            reg.counter("serve_requests_rejected").value),
        "tokens_total": int(reg.counter("serve_tokens_total").value),
        "decode_steps_total": int(
            reg.counter("serve_decode_steps_total").value),
        "prefills_total": int(
            reg.counter("serve_prefills_total").value),
    }
    for name, key in (("serve_ttft_s", "ttft"),
                      ("serve_token_s", "token_latency"),
                      ("serve_e2e_s", "e2e"),
                      ("serve_prefill_s", "prefill")):
        hist = reg.histogram(name)
        summ = hist.summary()
        for stat in ("p50", "p90", "p99", "mean", "count"):
            if stat in summ:
                record[f"{key}_{stat}_s" if stat != "count"
                       else f"{key}_count"] = (
                    round(summ[stat], 6) if stat != "count"
                    else int(summ[stat]))
        if key in ("ttft", "e2e") and summ:
            record[f"{key}_sample"] = [
                round(v, 6) for v in hist.export_sample()]
            if summ.get("approx"):
                record[f"{key}_approx"] = 1
    # Paged-KV pool state (serve_kv_* gauges; zeros on a dense pool):
    # the capacity signal a fleet operator sizes --kv-pages from.
    for gauge_name, field in (("serve_kv_pages_total", "kv_pages_total"),
                              ("serve_kv_pages_used", "kv_pages_used")):
        val = reg.gauge(gauge_name).value
        record[field] = int(val) if val is not None else 0
    bpt = reg.gauge("serve_kv_bytes_per_token").value
    record["kv_bytes_per_token"] = (round(float(bpt), 2)
                                    if bpt is not None else 0)
    # Prefix KV cache (serve_prefix_* instruments; zeros when the
    # cache is off): hit rate is THE steering signal — the router's
    # affinity and the fleet's shared-prefix traffic shape show up
    # here as prefill compute avoided.
    for cname, field in (
            ("serve_prefix_lookups_total", "prefix_lookups_total"),
            ("serve_prefix_hits_total", "prefix_hits_total"),
            ("serve_prefix_hit_tokens_total", "prefix_hit_tokens_total"),
            ("serve_prefix_inserts_total", "prefix_inserts_total"),
            ("serve_prefix_evictions_total", "prefix_evictions_total"),
            ("serve_prefix_cow_total", "prefix_cow_total"),
            ("serve_prefix_spills_total", "prefix_spills_total"),
            ("serve_prefix_warm_loads_total", "prefix_warm_loads_total")):
        record[field] = int(reg.counter(cname).value)
    pages_cached = reg.gauge("serve_prefix_pages_cached").value
    record["prefix_pages_cached"] = (int(pages_cached)
                                     if pages_cached is not None else 0)
    lookups = record["prefix_lookups_total"]
    record["prefix_hit_rate"] = (
        round(record["prefix_hits_total"] / lookups, 4) if lookups
        else 0.0)
    # Speculative decoding (serve_spec_* instruments; zeros with spec
    # off): acceptance rate is THE drafter-quality signal — a drafter
    # that stops matching its serving model shows up here before it
    # shows up as a tokens/s regression.
    for cname, field in (
            ("serve_spec_draft_tokens_total", "spec_draft_tokens_total"),
            ("serve_spec_accepted_tokens_total",
             "spec_accepted_tokens_total"),
            ("serve_spec_rejected_tokens_total",
             "spec_rejected_tokens_total"),
            ("serve_spec_verify_steps_total", "spec_verify_steps_total")):
        record[field] = int(reg.counter(cname).value)
    drafted = record["spec_draft_tokens_total"]
    record["spec_acceptance_rate"] = (
        round(record["spec_accepted_tokens_total"] / drafted, 4)
        if drafted else 0.0)
    verifies = record["spec_verify_steps_total"]
    record["spec_accepted_tokens_per_verify"] = (
        round(record["spec_accepted_tokens_total"] / verifies, 4)
        if verifies else 0.0)
    if final:
        record["final"] = True
    return record


def build_aot_store(directory: str, model_cfg, serve_cfg):
    """The engine's ``AotProgramStore`` (tpunet/utils/cache.py), keyed
    by every config field that selects a compiled program: the model
    architecture plus the pool shape. A replica booted with a different
    width/depth/slots gets a clean store MISS, never a wrong program
    (the store key additionally folds in jax version + device kind)."""
    import dataclasses

    from tpunet.utils.cache import AotProgramStore

    digest = AotProgramStore.digest({
        "model": dataclasses.asdict(model_cfg),
        "slots": serve_cfg.slots,
        "prefill_buckets": list(serve_cfg.prefill_buckets),
        # The paged-KV + sampling levers each select a DIFFERENT
        # compiled program (pool layout, fused sampler, page dtype):
        # fold them in so flipping a flag is a clean miss, never a
        # stale executable.
        "paged_kv": serve_cfg.paged_kv,
        "kv_pages": serve_cfg.kv_pages,
        "kv_page_tokens": serve_cfg.kv_page_tokens,
        "kv_dtype": serve_cfg.kv_dtype,
        "device_sampling": serve_cfg.device_sampling,
        # Spec-decode levers select a different program SET (drafter
        # width changes the drafter executables, K changes the verify
        # width): spec-on and spec-off engines must never share blobs.
        "spec_decode": getattr(serve_cfg, "spec_decode", False),
        "spec_k": getattr(serve_cfg, "spec_k", 4),
        "spec_draft_width_mult": getattr(
            serve_cfg, "spec_draft_width_mult", 0.5),
    })
    return AotProgramStore(directory, digest)


class _Slot:
    """Host-side bookkeeping for one KV-cache row."""

    __slots__ = ("req", "pos", "next_token", "generated", "pages",
                 "pinned", "seq")

    def __init__(self, req: GenerateRequest, pos: int, next_token: int,
                 generated: int = 1, seq: int = 0):
        self.req = req
        self.pos = pos            # next cache write position
        self.next_token = next_token
        self.generated = generated  # tokens produced (resume-aware)
        self.pages: List[int] = []  # PRIVATE paged-KV pages (table
        #                             indices from len(pinned) up)
        self.pinned: List = []    # prefix-cache nodes this slot maps
        #                           read-only (table indices 0..k-1)
        self.seq = seq            # admission ordinal (preempt youngest)


class Engine:
    """Slot-pool continuous-batching engine for one LM.

    ``model``/``variables`` come from ``infer.generate.load_lm`` (pass
    the same ``mesh`` for tensor-parallel serving — the KV pool is then
    created sharded over the mesh 'model' axis to match the attention's
    head-sharded writes). The engine owns a single background thread;
    ``submit`` is thread-safe and non-blocking (bounded queue).
    """

    def __init__(self, model, variables, cfg, *, registry=None,
                 mesh=None, aot_store=None, prefix_store=None,
                 drafter_params=None):
        import jax
        import jax.numpy as jnp

        from tpunet.obs.registry import Registry

        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.mesh = mesh
        self.registry = registry if registry is not None else Registry()
        self.max_seq_len = int(model.max_len)
        self.slots = int(cfg.slots)
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {cfg.slots}")
        self.buckets = tuple(sorted(
            b for b in cfg.prefill_buckets if b <= self.max_seq_len))
        if not self.buckets:
            self.buckets = (self.max_seq_len,)
        self.queue = RequestQueue(cfg.queue_max,
                                  on_finish=self._account_finish)
        self._active: List[Optional[_Slot]] = [None] * self.slots

        # -- paged KV geometry (host-owned allocator) ------------------
        self.device_sampling = bool(cfg.device_sampling)
        self.page_tokens = int(cfg.kv_page_tokens)
        if self.page_tokens < 1:
            raise ValueError(
                f"kv_page_tokens must be >= 1, got {cfg.kv_page_tokens}")
        self.pages_per_slot = -(-self.max_seq_len // self.page_tokens)
        self._paged_kv = None
        if cfg.paged_kv:
            from tpunet.models.vit import PagedKV
            usable = int(cfg.kv_pages) or self.slots * self.pages_per_slot
            if usable < 1:
                raise ValueError(f"kv_pages must be >= 1, got "
                                 f"{cfg.kv_pages}")
            self.kv_pages_usable = usable
            # Free list yields ascending page ids (pop from the end);
            # freed pages re-enter at the end, so recycling is LIFO —
            # a just-freed hot page is the next one handed out.
            self._free_pages = list(range(usable, 0, -1))
            self._page_table = np.zeros(
                (self.slots, self.pages_per_slot), np.int32)
            # pages + 1: page 0 is the reserved garbage page (inactive
            # rows and padded prefill tails write there; the allocator
            # never hands it out).
            self._paged_kv = PagedKV(pages=usable + 1,
                                     page_tokens=self.page_tokens,
                                     dtype=cfg.kv_dtype)
            self._kv_pages_touched: set = set()
        elif cfg.kv_dtype not in ("auto",):
            raise ValueError(
                f"kv_dtype={cfg.kv_dtype!r} requires the paged KV "
                "cache (drop --no-paged-kv or use kv_dtype auto)")
        # -- prefix KV cache (tpunet/serve/prefixcache/) ---------------
        # Refcounted content-addressed pages INSIDE the page pool:
        # admission pins the longest cached page-aligned prefix into
        # the new slot's table (zero prefill compute for those pages)
        # and re-prefills only the suffix. Bounded below the pool so
        # paying slots always have headroom; LRU-evicted back to the
        # free list under pool pressure. Requires paging (the dense
        # pool has no page identity to share).
        self._prefix = None
        self._prefix_store = None
        if self._paged_kv is not None \
                and getattr(cfg, "prefix_cache", False):
            cap = int(getattr(cfg, "prefix_cache_pages", 0))
            if cap <= 0:
                cap = self.kv_pages_usable // 2
            if cap > 0:
                from tpunet.serve.prefixcache import PrefixCache
                self._prefix = PrefixCache(self.page_tokens, cap,
                                           registry=self.registry)
                self._prefix_store = prefix_store
        # -- speculative decoding (tpunet/serve/spec.py) ---------------
        # A narrow drafter proposes spec_k tokens per active slot
        # against its OWN paged pool, then ONE [slots, K+1]-wide
        # verify over the main pool scores them — up to K+1 verified
        # tokens per slot per cycle. The drafter pool shares THIS
        # page table (identical geometry: same page ids, same
        # page_tokens), so allocate-on-advance, cursor rewind,
        # release, and preemption keep both pools in lockstep with
        # zero extra allocator state. Every emitted token comes from
        # the verify program, so the stream is bitwise identical to
        # spec-off at any acceptance rate.
        self.spec_decode = bool(getattr(cfg, "spec_decode", False))
        self.spec_k = int(getattr(cfg, "spec_k", 4))
        self._drafter_model = None
        self._drafter_params = None
        self._draft_cache = None
        self._drafter_paged_kv = None
        if self.spec_decode:
            if self._paged_kv is None:
                raise ValueError(
                    "spec_decode requires the paged KV cache (drop "
                    "--no-paged-kv): rejection is a page-table cursor "
                    "rewind")
            if not self.device_sampling:
                raise ValueError(
                    "spec_decode requires device sampling (drop "
                    "--no-device-sampling): acceptance compares the "
                    "drafter against the fused sampler's per-"
                    "(seed, step) choices")
            if self.spec_k < 1:
                raise ValueError(
                    f"spec_k must be >= 1, got {cfg.spec_k}")
            wm = float(getattr(cfg, "spec_draft_width_mult", 0.5))
            if wm <= 0:
                raise ValueError(
                    "spec_draft_width_mult must be > 0, got "
                    f"{wm}")
            if wm == 1.0:
                # Self-speculation: the drafter IS the serving model
                # (still with its own pool — it runs ahead of the
                # verified cursor). 100% acceptance by construction;
                # useful for parity tests, never a throughput win.
                self._drafter_model = model
                self._drafter_params = variables["params"]
            else:
                if not hasattr(model, "hidden") \
                        or not hasattr(model, "heads"):
                    raise ValueError(
                        "spec_draft_width_mult != 1.0 needs a model "
                        "with width levers (TransformerLM); got "
                        f"{type(model).__name__}")
                heads = int(model.heads)
                dh = max(heads, int(int(model.hidden) * wm)
                         // heads * heads)
                self._drafter_model = model.clone(hidden=dh)
                self._drafter_params = None   # resolved below
            from tpunet.models.vit import PagedKV
            self._drafter_paged_kv = PagedKV(
                pages=self.kv_pages_usable + 1,
                page_tokens=self.page_tokens, dtype=cfg.kv_dtype)
            if drafter_params is not None:
                # In-memory drafter weights (bench_serve --spec fits
                # the drafter to its workload and injects it here).
                self._drafter_params = drafter_params
            elif self._drafter_params is None:
                import jax as _jax
                from tpunet.models import init_variables
                template = init_variables(
                    self._drafter_model, _jax.random.PRNGKey(0),
                    seq_len=min(16, self.max_seq_len))["params"]
                ckpt = getattr(cfg, "spec_draft_checkpoint", "")
                if ckpt:
                    from tpunet.serve import spec as serve_spec
                    self._drafter_params = \
                        serve_spec.load_drafter_params(ckpt, template)
                else:
                    # Deterministic random init: correct (acceptance
                    # just tends to zero) but pointless for
                    # throughput — fit a drafter for real traffic.
                    self._drafter_params = template
        self._page_ops = None        # (read, write, copy) jitted lazily
        self._admit_seq = 0
        self.peak_active_slots = 0   # high-water mark (bench_serve
        #                              --slots-sweep admitted-slot count)
        # Serve-tier fault injector (--chaos, tpunet/serve/chaos.py):
        # the engine fires token/prefill/stall hooks, the HTTP
        # frontend the probe/stream ones. None when unarmed.
        from tpunet.serve import chaos as serve_chaos
        self.chaos = serve_chaos.install(getattr(cfg, "chaos", ""))
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drain_kill = threading.Event()
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_handle = None       # flightrec registry handle
        self.error: Optional[str] = None
        self._last_emit = time.perf_counter()
        self._started = time.perf_counter()

        # -- device programs (compiled lazily, one per shape) ----------
        # One callable; jit specializes per token shape: [N, 1] decode
        # plus one [N, Lb] program per prefill bucket. The cache is
        # donated — it is the engine's single biggest buffer and every
        # call replaces it. With device sampling the batched sampler
        # is FUSED onto the step (the program returns sampled int32
        # tokens, not logits); with paging the per-slot page table
        # rides along as one small int32 input.
        paged_kv = self._paged_kv
        fuse_sampler = self.device_sampling

        def _masked_step(params, cache, tokens, positions, active,
                         *extra):
            i = 0
            page_table = None
            if paged_kv is not None:
                page_table = extra[i]
                i += 1
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tokens, train=False,
                decode=True, pos_offset=positions, decode_active=active,
                paged_kv=paged_kv, page_table=page_table,
                mutable=["cache"])
            if not fuse_sampler:
                return mutated["cache"], logits
            from tpunet.serve.sampling import batched_sample
            last_idx, temp, top_k, top_p, seeds, steps = extra[i:i + 6]
            rows = jnp.take_along_axis(
                logits, last_idx[:, None, None],
                axis=1)[:, 0].astype(jnp.float32)
            toks = batched_sample(rows, temp, top_k, top_p, seeds,
                                  steps)
            return mutated["cache"], toks

        self._step = jax.jit(_masked_step, donate_argnums=(1,))
        self._cache = self._make_cache()
        self._inactive_tok = np.zeros((self.slots, 1), np.int32)
        self._zero_idx = np.zeros((self.slots,), np.int32)
        if self._drafter_model is not None:
            self._draft_cache = self._make_cache(
                model=self._drafter_model,
                paged_kv=self._drafter_paged_kv)
            self._build_spec_programs()
        self._init_kv_gauges()
        # AOT warm-start (tpunet/utils/cache.py AotProgramStore): the
        # engine's program set is closed — [N, 1] decode + one [N, Lb]
        # per bucket — so fully-compiled executables deserialize at
        # boot and the jit path above becomes the fallback for shapes
        # the store has never seen. Single-device only: a sharded pool
        # would bake device assignments into the executable.
        self._aot: dict = {}
        self.aot_status: dict = {}
        if aot_store is not None and mesh is None:
            self._warm_start_aot(aot_store)
        # Prefix warm-start AFTER the pool exists and BEFORE the
        # engine thread runs: a respawned/scaled-up replica adopts the
        # fleet's spilled prefix set instead of cold KV, so its very
        # first shared-prefix request prefills only the suffix.
        if self._prefix is not None and self._prefix_store is not None:
            self._warm_start_prefix()

    def _warm_start_aot(self, store) -> None:
        """Load (or compile-and-save) every program the pool can run.
        Deserialization skips tracing/lowering/XLA entirely — the
        compile-bound replica cold-start becomes an mmap + relink."""
        import jax

        def sds(tree):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

        params_s = sds(self.variables["params"])
        cache_s = sds(self._cache)
        i32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.int32)  # noqa: E731
        f32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731
        pos_s = i32(self.slots)
        act_s = jax.ShapeDtypeStruct((self.slots,), bool)
        extra_s = []
        if self._paged_kv is not None:
            extra_s.append(i32(self.slots, self.pages_per_slot))
        if self.device_sampling:
            extra_s += [i32(self.slots), f32(self.slots),
                        i32(self.slots), f32(self.slots),
                        i32(self.slots), i32(self.slots)]
        for width in (1,) + self.buckets:
            tag = f"w{width}"
            toks_s = jax.ShapeDtypeStruct((self.slots, width), np.int32)
            program = store.load("masked_step", tag)
            if program is None:
                # Compile fresh (persistent compile cache off): a
                # cache-served executable saves a poison blob that
                # fails to deserialize at the next boot.
                from tpunet.utils.cache import serializable_compile
                with serializable_compile():
                    program = self._step.lower(
                        params_s, cache_s, toks_s, pos_s, act_s,
                        *extra_s).compile()
                saved = store.save("masked_step", tag, program)
                self.aot_status[tag] = ("compiled+saved" if saved
                                        else "compiled")
            else:
                self.aot_status[tag] = "loaded"
            self._aot[width] = program
        if self._drafter_model is None:
            return
        # Spec programs are part of the replica's closed program set
        # too: drafter prefill per bucket, the K+1 draft burst, and
        # the [slots, K+1] verify — a spec-on replica cold-starts
        # without tracing just like a spec-off one. The store digest
        # folds the spec levers, so spec-on/off never share blobs.
        dparams_s = sds(self._drafter_params)
        dcache_s = sds(self._draft_cache)
        samp_s = (f32(self.slots), i32(self.slots), f32(self.slots),
                  i32(self.slots), i32(self.slots))
        k = self.spec_k
        programs = []
        # Burst/verify are compiled per attention-window bucket (the
        # engine slices the page table to the live window at call
        # time); the full closed set is log2(pages_per_slot) pairs.
        for win in self._spec_window_buckets:
            win_s = i32(self.slots, win)
            programs.append(
                ("spec_draft_burst", f"k{k}w{win}",
                 self._draft_burst_fn,
                 (dparams_s, dcache_s, i32(self.slots), pos_s, act_s,
                  win_s) + samp_s))
            programs.append(
                ("spec_verify", f"k{k}w{win}", self._verify_fn,
                 (params_s, cache_s, i32(self.slots, k + 1), pos_s,
                  act_s, win_s) + samp_s))
        for width in self.buckets:
            win = self._spec_window(
                (width - 1) // self.page_tokens + 1)
            programs.append(
                ("spec_draft_prefill", f"w{width}",
                 self._draft_prefill_fn,
                 (dparams_s, dcache_s, i32(self.slots, width), pos_s,
                  act_s, i32(self.slots, win))))
        from tpunet.utils.cache import serializable_compile
        for name, tag, fn, shapes in programs:
            program = store.load(name, tag)
            if program is None:
                with serializable_compile():
                    program = fn.lower(*shapes).compile()
                saved = store.save(name, tag, program)
                self.aot_status[f"{name}-{tag}"] = (
                    "compiled+saved" if saved else "compiled")
            else:
                self.aot_status[f"{name}-{tag}"] = "loaded"
            self._spec_aot[(name, tag)] = program

    def _dispatch_step(self, toks, positions, active, last_idx=None):
        """Run one masked-step program: the AOT executable for this
        token width when warm-started, the jit fallback otherwise.
        Returns (cache, logits) host-sampling, (cache, tokens) with
        the fused device sampler."""
        program = self._aot.get(toks.shape[1])
        if program is None:
            program = self._step
        args = [self.variables["params"], self._cache, toks, positions,
                active]
        if self._paged_kv is not None:
            args.append(self._page_table)
        if self.device_sampling:
            args.extend(self._sampling_args(
                last_idx if last_idx is not None else self._zero_idx))
        return program(*args)

    def _sampling_args(self, last_idx):
        """Per-slot sampling parameters for the fused device sampler:
        temperature/top-k/top-p/seed from each resident request, plus
        each slot's generated-token count (the per-step key fold — a
        preempted-and-resumed request continues its exact sample
        stream)."""
        n = self.slots
        temp = np.zeros(n, np.float32)
        top_k = np.zeros(n, np.int32)
        top_p = np.zeros(n, np.float32)
        seeds = np.zeros(n, np.int32)
        steps = np.zeros(n, np.int32)
        for i, slot in enumerate(self._active):
            if slot is None:
                continue
            r = slot.req
            temp[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            seeds[i] = r.seed    # admission-validated into [0, 2**31)
            steps[i] = len(r.tokens)
        return [np.asarray(last_idx, np.int32), temp, top_k, top_p,
                seeds, steps]

    # -- speculative-decoding programs (docs/serving.md) ----------------

    def _build_spec_programs(self) -> None:
        """Three jitted spec programs, all [slots]-wide and masked
        like the main step (one compile each, AOT-serializable):

        - drafter prefill: write-only full-prompt pass filling the
          drafter pool (per prefill bucket).
        - draft burst: K+1 fused drafter steps. Iteration j consumes
          token t_j at position pos+j, writes drafter K/V there, and
          samples d_{j+1} with the SAME (seed, step=s0+j) key the
          verifier will use — lockstep keys are what make a perfect
          drafter accept at temperature > 0 too. The K+1'th draft is
          discarded, but its K/V write keeps the drafter pool gapless
          after a full acceptance (both cursors then cover pos+K).
        - verify: ONE [slots, K+1] forward over the main pool scoring
          [next_token, d_1..d_K] at positions pos..pos+K, sampling
          choice c_j per position with step s0+j.
        """
        import jax
        import jax.numpy as jnp

        from tpunet.serve.sampling import (batched_sample,
                                           batched_sample_positions)

        dmodel = self._drafter_model
        dpaged = self._drafter_paged_kv
        model = self.model
        paged = self._paged_kv
        k = self.spec_k

        def _draft_prefill(params, cache, tokens, positions, active,
                           page_table):
            _, mutated = dmodel.apply(
                {"params": params, "cache": cache}, tokens,
                train=False, decode=True, pos_offset=positions,
                decode_active=active, paged_kv=dpaged,
                page_table=page_table, mutable=["cache"])
            return mutated["cache"]

        def _draft_burst(params, cache, first_tok, positions, active,
                         page_table, temp, top_k, top_p, seeds,
                         steps0):
            def body(carry, j):
                cache, tok = carry
                logits, mutated = dmodel.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    train=False, decode=True, pos_offset=positions + j,
                    decode_active=active, paged_kv=dpaged,
                    page_table=page_table, mutable=["cache"])
                nxt = batched_sample(
                    logits[:, 0].astype(jnp.float32), temp, top_k,
                    top_p, seeds, steps0 + j)
                return (mutated["cache"], nxt), nxt
            (cache, _), drafts = jax.lax.scan(
                body, (cache, first_tok),
                jnp.arange(k + 1, dtype=jnp.int32))
            # drafts is [K+1, B] = d_1..d_{K+1}; d_{K+1} lies beyond
            # the verify window and is dropped.
            return cache, drafts[:k].T

        def _verify(params, cache, tokens, positions, active,
                    page_table, temp, top_k, top_p, seeds, steps0):
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tokens,
                train=False, decode=True, pos_offset=positions,
                decode_active=active, paged_kv=paged,
                page_table=page_table, mutable=["cache"])
            choices = batched_sample_positions(
                logits.astype(jnp.float32), temp, top_k, top_p,
                seeds, steps0)
            return mutated["cache"], choices

        self._draft_prefill_fn = jax.jit(_draft_prefill,
                                         donate_argnums=(1,))
        self._draft_burst_fn = jax.jit(_draft_burst,
                                       donate_argnums=(1,))
        self._verify_fn = jax.jit(_verify, donate_argnums=(1,))
        self._spec_aot: dict = {}
        # Attention-window buckets for the spec programs, in PAGE
        # SLOTS (columns of the page table). The paged attend derives
        # its whole key window from ``page_table.shape[1]`` — gather
        # size, score matrix, mask — so slicing the table to the
        # smallest bucket covering every burst slot's pos+K shrinks
        # the verify/burst attention from O(max_seq_len) keys to
        # O(live sequence) with NO model change, and the extra
        # (masked, exp->0) columns it drops contribute exactly zero,
        # so outputs stay bitwise identical across buckets. Doubling
        # buckets bound the compile count at log2(pages_per_slot).
        buckets, w = [], 4
        while w < self.pages_per_slot:
            buckets.append(w)
            w *= 2
        buckets.append(self.pages_per_slot)
        self._spec_window_buckets = tuple(buckets)

    def _spec_window(self, need_slots: int) -> int:
        """Smallest window bucket covering ``need_slots`` page-table
        columns (attention window for a spec program call)."""
        for w in self._spec_window_buckets:
            if w >= need_slots:
                return w
        return self._spec_window_buckets[-1]

    def _dispatch_spec(self, name: str, tag: str, fallback, args):
        """Run one spec program: the AOT executable when warm-started,
        the jit fallback otherwise (mirrors ``_dispatch_step``)."""
        program = self._spec_aot.get((name, tag))
        if program is None:
            program = fallback
        return program(*args)

    def drafter_pool_bytes(self) -> int:
        """Resident bytes of the drafter's KV pool (0 with spec off) —
        reported separately from ``kv_pool_bytes`` because the drafter
        pool is the spec lever's EXTRA memory cost (width 0.5 ≈ +50%
        KV bytes), and the bench must account for it honestly."""
        import jax
        if self._draft_cache is None:
            return 0
        return int(sum(leaf.nbytes
                       for leaf in jax.tree_util.tree_leaves(
                           self._draft_cache)))

    # -- pool construction ---------------------------------------------

    def _make_cache(self, model=None, paged_kv=None):
        import jax
        import jax.numpy as jnp
        model = model if model is not None else self.model
        if paged_kv is None:
            paged_kv = self._paged_kv
        init_kw = {}
        if paged_kv is not None:
            init_kw = dict(
                paged_kv=paged_kv,
                page_table=jnp.zeros((self.slots, self.pages_per_slot),
                                     jnp.int32))
        shapes = jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((self.slots, self.max_seq_len), jnp.int32),
                decode=True, **init_kw))

        def zeros(s):
            if self.mesh is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                tp = self.mesh.shape.get("model", 1)
                if s.ndim == 4 and tp > 1 and s.shape[2] % tp == 0:
                    spec = P(None, None, "model", None)   # dense pool
                elif s.ndim == 3 and tp > 1 and s.shape[1] % tp == 0:
                    spec = P(None, "model", None)         # page pool
                else:
                    spec = P()
                return jnp.zeros(s.shape, s.dtype,
                                 device=NamedSharding(self.mesh, spec))
            return jnp.zeros(s.shape, s.dtype)

        return jax.tree_util.tree_map(zeros, shapes["cache"])

    def kv_pool_bytes(self) -> int:
        """Resident bytes of the KV cache tree (page pool + scales
        when paged; the dense [slots, max_seq_len] pool otherwise) —
        the capacity number ``bench_serve.py`` reports per slot."""
        import jax
        return int(sum(leaf.nbytes
                       for leaf in jax.tree_util.tree_leaves(
                           self._cache)))

    def kv_bytes_per_token(self) -> float:
        """KV bytes pinned per cacheable token position across the
        whole pool (pages incl. scale sidecars / dense rows)."""
        if self._paged_kv is not None:
            rows = self._paged_kv.pages * self.page_tokens
        else:
            rows = self.slots * self.max_seq_len
        return self.kv_pool_bytes() / max(1, rows)

    def _init_kv_gauges(self) -> None:
        reg = self.registry
        reg.gauge("serve_kv_bytes_per_token").set(
            round(self.kv_bytes_per_token(), 2))
        if self._paged_kv is not None:
            reg.gauge("serve_kv_pages_total").set(self.kv_pages_usable)
            reg.gauge("serve_kv_pages_used").set(0)
        if self._prefix is not None:
            reg.gauge("serve_prefix_pages_cached").set(0)

    def _update_kv_gauges(self) -> None:
        if self._paged_kv is not None:
            self.registry.gauge("serve_kv_pages_used").set(
                self.kv_pages_usable - len(self._free_pages))

    # -- paged-KV page allocator (engine thread only) -------------------

    def _alloc_pages_for(self, slot_i: int, n_tokens: int,
                         first_index: int = 0):
        """Allocate pages covering ``n_tokens`` prefill positions for
        an admission, starting at page-table index ``first_index``
        (indices below it are prefix-cache pins); None when the pool
        cannot cover it right now (the request stays queued).
        All-or-nothing. Under pressure, unpinned prefix-cache pages
        are LRU-evicted back to the free list first — cached pages
        never starve a paying admission."""
        need = -(-n_tokens // self.page_tokens) - first_index
        while len(self._free_pages) < need:
            if not self._evict_prefix_page():
                return None
        pages = [self._free_pages.pop() for _ in range(need)]
        for j, p in enumerate(pages):
            self._page_table[slot_i, first_index + j] = p
        self._kv_pages_touched.update(pages)
        self.registry.counter("serve_kv_page_allocs_total").inc(need)
        return pages

    def _ensure_page_capacity(self, slot_i: int, slot: _Slot,
                              through_pos: int = -1) -> bool:
        """Allocate-on-advance: make sure the page covering the slot's
        next write position exists (pinned prefix pages count toward
        coverage; new pages are always PRIVATE — decode never writes a
        shared page). ``through_pos`` extends coverage to a LATER
        position (a spec burst writes pos..pos+K in one cycle; the
        over-allocation is what the rejection rewind recycles). False
        = pool exhausted even after evicting every evictable prefix
        page (the slot sits this iteration out, or gets preempted)."""
        need = max(slot.pos, through_pos) // self.page_tokens + 1
        while len(slot.pinned) + len(slot.pages) < need:
            if not self._free_pages and not self._evict_prefix_page():
                return False
            p = self._free_pages.pop()
            self._page_table[slot_i,
                             len(slot.pinned) + len(slot.pages)] = p
            slot.pages.append(p)
            self._kv_pages_touched.add(p)
            self.registry.counter("serve_kv_page_allocs_total").inc()
        return True

    def _release_pages(self, slot_i: int, slot: _Slot) -> None:
        """Free-on-finish with recycling: the slot's PRIVATE pages
        re-enter the free list (LIFO), its prefix pins drop their
        refcount (the pages stay cached — eviction, not release,
        returns them to the pool), and its table row resets to the
        garbage page."""
        if self._paged_kv is None:
            return
        self._free_pages.extend(slot.pages)
        slot.pages = []
        if slot.pinned:
            self._prefix.unpin(slot.pinned)
            slot.pinned = []
        self._page_table[slot_i, :] = 0
        self._update_kv_gauges()

    def _evict_prefix_page(self) -> bool:
        """Pool-pressure relief valve: LRU-evict one unpinned prefix
        page back to the free list. False when the cache is off or
        everything cached is pinned by a live slot (then the normal
        preempt/completability logic takes over — pins are released by
        finish AND by preemption, so cached pages can never deadlock a
        request the completability guard admitted)."""
        if self._prefix is None:
            return False
        page = self._prefix.evict_one()
        if page is None:
            return False
        self._free_pages.append(page)
        return True

    # -- prefix-cache page ops (engine thread / init only) --------------

    def _build_page_ops(self):
        """Three tiny jitted programs over the whole paged cache tree
        (every leaf is flat-row-indexed ``[pages * page_tokens, ...]``
        — K/V pages and their scale sidecars alike): read one page's
        rows to a host-transferable tree, scatter rows into a page,
        and device-copy page -> page (the COW divergence copy). Page
        indices are traced scalars, so ONE compiled program covers
        every page."""
        import jax
        from jax import lax
        pt = self.page_tokens

        def read(cache, page):
            start = page * pt
            return jax.tree_util.tree_map(
                lambda leaf: lax.dynamic_slice_in_dim(
                    leaf, start, pt, axis=0), cache)

        def write(cache, rows, page):
            start = page * pt
            return jax.tree_util.tree_map(
                lambda leaf, r: lax.dynamic_update_slice_in_dim(
                    leaf, r.astype(leaf.dtype), start, axis=0),
                cache, rows)

        def copy(cache, src, dst):
            return write(cache, read(cache, src), dst)

        return (jax.jit(read),
                jax.jit(write, donate_argnums=(0,)),
                jax.jit(copy, donate_argnums=(0,)))

    def _page_ops_lazy(self):
        if self._page_ops is None:
            self._page_ops = self._build_page_ops()
        return self._page_ops

    def _copy_page(self, src: int, dst: int) -> None:
        """Device-copy one pool page (COW at the divergence page: the
        fresh private copy takes the suffix write, the shared source
        stays immutable)."""
        _, _, copy = self._page_ops_lazy()
        self._cache = copy(self._cache, np.int32(src), np.int32(dst))

    def _read_page_rows(self, page: int) -> list:
        """One page's rows as host numpy leaves in flatten order (the
        spill payload; the store digest guarantees the reader's tree
        matches)."""
        import jax
        read, _, _ = self._page_ops_lazy()
        rows = read(self._cache, np.int32(page))
        return [np.asarray(leaf) for leaf in
                jax.tree_util.tree_leaves(jax.device_get(rows))]

    def _spill_prefix_page(self, node, parent_digest: str) -> None:
        """Write-through one freshly-inserted prefix page to the
        shared store (fsatomic first-writer-wins: N replicas spilling
        the fleet-common system prefix commit it once). Best-effort —
        a read-only disk degrades to a per-replica cache."""
        if self._prefix_store is None \
                or self._prefix_store.exists(node.digest):
            return
        rows = self._read_page_rows(node.page)
        if self._prefix_store.save(node.digest, parent_digest,
                                   node.depth, rows):
            self.registry.counter("serve_prefix_spills_total").inc()

    def _warm_start_prefix(self) -> None:
        """Adopt the fleet's spilled prefix set into this replica's
        pool at boot (depth order: a page is adopted only under its
        already-adopted parent, so a capacity- or pool-truncated load
        still leaves a prefix-closed trie). Bounded by the cache
        capacity AND the free list — warm pages are all evictable, so
        they can never crowd out the first real admission."""
        import jax
        from tpunet.serve.prefixcache import keys as pk
        leaves, treedef = jax.tree_util.tree_flatten(self._cache)
        _, write, _ = self._page_ops_lazy()
        loaded = 0
        for entry in self._prefix_store.load_all(
                limit=self._prefix.capacity):
            digest = entry.get("digest", "")
            depth = int(entry.get("depth", 0))
            rows = entry.get("rows")
            if not digest or self._prefix.get(digest) is not None:
                continue
            parent = None
            if depth > 0:
                parent = self._prefix.get(entry.get("parent", pk.ROOT))
                if parent is None or parent.depth != depth - 1:
                    continue      # orphan: its parent didn't make it
            if not isinstance(rows, list) or len(rows) != len(leaves) \
                    or any(r.shape != (self.page_tokens,) + tuple(
                        leaf.shape[1:])
                        for r, leaf in zip(rows, leaves)):
                continue          # foreign/torn entry: skip, not crash
            if self._prefix.pages_cached >= self._prefix.capacity \
                    or not self._free_pages:
                break
            page = self._free_pages.pop()
            self._kv_pages_touched.add(page)
            rows_tree = jax.tree_util.tree_unflatten(treedef, rows)
            self._cache = write(self._cache, rows_tree, np.int32(page))
            self._prefix.insert(digest, parent, depth, page)
            loaded += 1
        if loaded:
            self.registry.counter(
                "serve_prefix_warm_loads_total").inc(loaded)
            self._update_kv_gauges()

    def _adopt_prefix_pages(self, slot_i: int, slot: _Slot,
                            resume: np.ndarray) -> None:
        """Post-prefill insert: every full page covered by the
        request's PROMPT (never decode-generated tokens — those are
        request-specific) becomes a cached, refcounted node. A
        concurrent duplicate (two same-prefix admissions in one batch
        both missed lookup) dedups here: the private page goes back to
        the free list and the slot repoints at the cached twin — the
        contents are bitwise-identical, both produced by the same
        deterministic prefill program. Capacity holds via LRU
        eviction; when nothing is evictable the page simply stays
        private."""
        from tpunet.serve.prefixcache import keys as pk
        pt = self.page_tokens
        full = int(slot.req.prompt.size) // pt
        prev = slot.pinned[-1] if slot.pinned else None
        for j in range(len(slot.pinned), full):
            digest = pk.token_prefix_digest(resume, (j + 1) * pt)
            node = self._prefix.get(digest)
            if node is not None:
                # Duplicate: recycle our private page, share theirs.
                self._free_pages.append(slot.pages.pop(0))
                self._page_table[slot_i, j] = node.page
            else:
                while self._prefix.pages_cached >= self._prefix.capacity:
                    if not self._evict_prefix_page():
                        return     # full of pinned pages: stay private
                node = self._prefix.insert(
                    digest, prev, j, slot.pages.pop(0))
                self._spill_prefix_page(
                    node, prev.digest if prev is not None else pk.ROOT)
            self._prefix.pin([node])
            slot.pinned.append(node)
            prev = node

    def _choose_preempt_victim(self, blocked) -> int:
        """Pick the slot index to preempt from ``blocked``
        [(slot_i, slot), ...]: the YOUNGEST admission whose resume
        prefill (prompt + generated) still fits a bucket. Preempting
        an unresumable slot turns transient pool pressure into a
        client-visible error, so one is chosen only when every
        blocked slot is unresumable (then the youngest fails —
        unavoidable, but never a healthy request while a resumable
        victim exists). Oldest-resumable-survives keeps forward
        progress: the surviving residents eventually finish and free
        pages."""
        largest = self.buckets[-1]
        resumable = [it for it in blocked
                     if it[1].req.prompt.size
                     + len(it[1].req.tokens) <= largest]
        pool = resumable if resumable else blocked
        return max(pool, key=lambda it: it[1].seq)[0]

    def _preempt_slot(self, slot_i: int) -> None:
        """Pool exhausted and nothing can advance: push the youngest
        blocked request back to the HEAD of the queue with its
        progress intact (tokens already streamed stay valid; on
        re-admission the engine re-prefills prompt+generated and the
        sample stream continues at its per-step key fold)."""
        slot = self._active[slot_i]
        self._active[slot_i] = None
        self._release_pages(slot_i, slot)
        req = slot.req
        req.preemptions += 1
        req._preempt_t = time.perf_counter()
        self.registry.counter("serve_kv_preemptions_total").inc()
        from tpunet.obs import flightrec
        flightrec.record("req", f"preempt {req.id}")
        if req.trace_id:
            tracing.crumb("preempt", req.trace_id, req.trace_hop,
                          rid=req.id)
        self.queue.requeue_front([req])
        self.registry.gauge("serve_active_slots").set(
            self.active_slots())
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())

    # -- public API ------------------------------------------------------

    def start(self) -> "Engine":
        # Host-thread registry (tpunet/obs/flightrec/): a decode
        # iteration wedged on the device past the budget pages
        # thread_stalled; idle waits (empty pool) do not.
        from tpunet.obs import flightrec
        self._thread_handle = flightrec.register_thread(
            "serve-engine", stall_after_s=120.0)
        flightrec.record("serve", f"engine start slots={self.slots}")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpunet-serve-engine")
        self._thread.start()
        return self

    @property
    def healthy(self) -> bool:
        return (self.error is None and self._thread is not None
                and self._thread.is_alive())

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def active_slots(self) -> int:
        return sum(1 for s in self._active if s is not None)

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLongError(
            f"prompt of {prompt_len} tokens exceeds the largest "
            f"prefill bucket ({self.buckets[-1]})")

    def submit(self, prompt, **kw) -> GenerateRequest:
        """Admit a request (or raise QueueFullError / DrainingError /
        PromptTooLongError / ValueError). The generation budget is
        clamped to the operator cap and the KV length, but never
        silently: ``req.requested_max_new_tokens`` keeps what the
        client asked for, ``req.max_new_tokens`` is the EFFECTIVE
        budget the frontend reports back. Never blocks."""
        if self.error is not None:
            from tpunet.serve.scheduler import DrainingError
            raise DrainingError(f"engine failed: {self.error}")
        kw.setdefault("max_new_tokens", self.cfg.default_max_new_tokens)
        requested = int(kw["max_new_tokens"])
        kw["max_new_tokens"] = min(requested,
                                   self.cfg.max_new_tokens_cap)
        if (kw.get("deadline_s") or 0) <= 0 \
                and self.cfg.default_deadline_s > 0:
            kw["deadline_s"] = self.cfg.default_deadline_s
        req = GenerateRequest(prompt, **kw)
        req.requested_max_new_tokens = requested
        try:
            n = int(req.prompt.size)
            # A cross-replica resume (router failover) re-prefills
            # prompt PLUS the journaled tokens: the combined length
            # must fit a bucket, like any preempt-resume.
            self.bucket_for(n + req.resume_offset)
            if n + req.max_new_tokens > self.max_seq_len:
                req.max_new_tokens = self.max_seq_len - n
                if req.max_new_tokens < 1:
                    raise PromptTooLongError(
                        f"prompt of {n} tokens leaves no room to "
                        f"generate (max_seq_len {self.max_seq_len})")
            if self._paged_kv is not None:
                # Completability guard: a request whose FULL length
                # cannot fit the page pool even alone would preempt
                # itself forever — reject it up front instead.
                worst = -(-(n + req.max_new_tokens) // self.page_tokens)
                if worst > self.kv_pages_usable:
                    raise PromptTooLongError(
                        f"request needs {worst} KV pages at full "
                        f"length but the pool has "
                        f"{self.kv_pages_usable}; lower "
                        "max_new_tokens or grow --kv-pages")
            if req.resume_offset and req.temperature > 0 \
                    and not self.device_sampling:
                # The sampled-continuation determinism guarantee rests
                # on the device sampler's counter-based (seed, step)
                # keys. The host sampler draws from a STATEFUL
                # generator — a resume would restart it at draw 0 and
                # diverge from the uninterrupted stream. Reject loudly
                # (the router degrades to the honest error frame)
                # rather than continue wrong.
                raise ValueError(
                    "sampled resume_tokens require device-side "
                    "sampling (counter-based per-(seed, step) keys); "
                    "this replica runs --no-device-sampling")
            if req.resume_offset and req.stop_token is not None \
                    and req.stop_token in req.tokens:
                # The journal already contains the stop token: the
                # donor died between streaming it and the done frame.
                # An uninterrupted run stops THERE — finish as 'stop'
                # without a slot, never generate past it.
                req.finish(FINISH_STOP)
                self._account_finish(req, FINISH_STOP)
                self.registry.counter("serve_requests_total").inc()
                return req
            if req.resume_offset \
                    and req.resume_offset >= req.max_new_tokens:
                # Mid-stream-failover resume whose journal already
                # meets the (possibly clamped) budget: the donor
                # replica died between its last token and the done
                # frame. Nothing to decode — finish as length without
                # ever taking a slot.
                req.finish(FINISH_LENGTH)
                self._account_finish(req, FINISH_LENGTH)
                self.registry.counter("serve_requests_total").inc()
                return req
            self.queue.submit(req)       # may raise QueueFull/Draining
        except Exception:
            self.registry.counter("serve_requests_rejected").inc()
            raise
        # Request-lifecycle breadcrumb into the flight-recorder ring:
        # submit -> prefill -> first_token -> finish become the
        # queue/prefill/decode phases on the unified timeline
        # (tpunet/obs/history/timeline.py). ~1-2 us each, no-op
        # without an armed recorder.
        from tpunet.obs import flightrec
        flightrec.record("req", f"submit {req.id} len={req.prompt.size}")
        if req.resume_offset:
            # Cross-replica resume (router failover): without this
            # mark the request's second half starts with a bare
            # prefill and the timeline can't tell a resumed stream
            # from a fresh one.
            flightrec.record(
                "req", f"resume {req.id} off={req.resume_offset}")
        if req.trace_id:
            tracing.crumb("submit", req.trace_id, req.trace_hop,
                          rid=req.id)
        self.registry.counter("serve_requests_total").inc()
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())
        self._wake.set()
        return req

    def _kill_survivors(self, reason: str) -> None:
        """Finish every in-flight and still-queued request with
        ``reason``, through the shared accounting. Only safe from the
        engine thread, or once it can no longer run."""
        for i, slot in enumerate(self._active):
            if slot is not None:
                self._finish_slot(i, reason)
        while True:
            reqs = self.queue.pop_ready(self.queue.queue_max)
            if not reqs:
                break
            for req in reqs:
                req.finish(reason)
                self._account_finish(req, reason)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting, let in-flight (and
        already-queued) requests finish, then stop the loop. Returns
        True when everything finished inside the timeout; leftovers are
        cancelled with finish_reason='drain'."""
        self._draining.set()
        waiting = self.queue.close()
        self._wake.set()
        if self._thread is None or not self._thread.is_alive():
            # Never started (or already dead): there is no loop to
            # finish the work — fail fast instead of waiting a budget
            # that can never be met.
            clean = self.active_slots() == 0 and not waiting
            self._kill_survivors(FINISH_DRAIN)
            self._stop.set()
            self._drained.set()
            return clean
        budget = timeout if timeout is not None \
            else self.cfg.drain_timeout_s
        clean = self._drained.wait(budget)
        if not clean:
            # Timeout: the ENGINE finishes survivors (in-flight and
            # still-queued alike) with reason 'drain' — through
            # _finish_slot so the serve_finished_drain counters and
            # e2e accounting stay truthful, and distinguishable from a
            # client-initiated cancel.
            self._drain_kill.set()
            self._wake.set()
            self._drained.wait(5.0)
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return clean

    def stop(self) -> None:
        """Hard stop (tests / error paths): cancel everything. Unlike
        cancel() alone, every in-flight request is FINISHED here —
        clients blocked in result()/events() must unblock now, not at
        their own timeout."""
        self._draining.set()
        self.queue.fail_all("engine stopped")
        for slot in list(self._active):
            if slot is not None:
                slot.req.cancel()
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        # The loop exits at the top of its while without a final reap:
        # finish whatever it left behind (thread joined or never ran,
        # so this is single-threaded now).
        self._kill_survivors(FINISH_CANCELLED)

    # -- engine loop -----------------------------------------------------

    def _run(self) -> None:
        from tpunet.obs import flightrec
        handle = self._thread_handle
        try:
            while not self._stop.is_set():
                # Claim busy only when there is (potential) work: an
                # empty iteration is a poll, not work, and marking it
                # busy would (a) lie to the thread_stalled watchdog
                # and (b) flood the flight-recorder ring with ~100
                # busy/idle transition events per second from an idle
                # server, evicting the request breadcrumbs the
                # timeline exporter needs. A wedged device call always
                # had work, so stall detection is unaffected.
                if (self.active_slots() or self.queue.depth()
                        or self._drain_kill.is_set()):
                    handle.beat("busy")
                else:
                    handle.beat("idle")
                did_work = self._iterate()
                if self._draining.is_set() and self.active_slots() == 0 \
                        and self.queue.depth() == 0:
                    break
                if not did_work:
                    handle.beat("idle")
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
            handle.beat("idle")
            self._emit_record(final=True)
        except BaseException as e:  # noqa: BLE001 — engine death is a
            # liveness event: surface through /healthz and fail every
            # request fast rather than hanging clients.
            self.error = f"{type(e).__name__}: {e}"
            flightrec.record("serve", f"engine error: {e}")
            for slot in self._active:
                if slot is not None:
                    slot.req.finish(FINISH_ERROR, error=self.error)
            self._active = [None] * self.slots
            self.queue.fail_all(self.error)
        finally:
            self._drained.set()

    def _iterate(self) -> bool:
        """One engine iteration: reap -> admit(prefill) -> decode.
        Returns False when there was nothing to do (caller sleeps)."""
        if self._drain_kill.is_set():
            # Drain timeout expired: everything still alive finishes
            # with reason 'drain' (the shutdown took it, not a client).
            self._kill_survivors(FINISH_DRAIN)
            return False
        if self.chaos is not None:
            self.chaos.maybe_stall()    # wedged-replica injection
        self._reap()
        admitted = self._admit()
        stepped = self._decode_iteration()
        now = time.perf_counter()
        if self.cfg.emit_every_s > 0 \
                and now - self._last_emit >= self.cfg.emit_every_s:
            self._emit_record()
        return admitted or stepped

    def _reap(self) -> None:
        """Free slots whose request was cancelled or hit its deadline
        (cooperative cancellation point)."""
        now = time.perf_counter()
        for i, slot in enumerate(self._active):
            if slot is None:
                continue
            if slot.req.cancelled:
                self._finish_slot(i, FINISH_CANCELLED)
            elif slot.req.expired(now):
                self._finish_slot(i, FINISH_DEADLINE)

    def _account_finish(self, req, reason: str) -> None:
        """Finish accounting shared by slot-finishes and requests the
        QUEUE finishes before they ever reach a slot: the counters must
        reconcile (requests_total == rejected + sum(finished_*))."""
        reg = self.registry
        from tpunet.obs import flightrec
        flightrec.record("req", f"finish {req.id} {reason}")
        reg.counter(f"serve_finished_{reason}").inc()
        if reason in (FINISH_LENGTH, FINISH_STOP):
            reg.counter("serve_requests_completed").inc()
        if req.e2e_s is not None:
            reg.histogram("serve_e2e_s").observe(req.e2e_s)
        if req.trace_id:
            # Close this hop's replica span: crumb for the timeline
            # join, one obs_trace record with the phase decomposition
            # for the fleet rollup. The empty-trace_id check above is
            # the whole cost on the unsampled path.
            tracing.crumb("finish", req.trace_id, req.trace_hop,
                          rid=req.id, reason=reason)
            record = tracing.build_trace_record(
                trace_id=req.trace_id, hop=req.trace_hop,
                role="replica", finish_reason=reason,
                queue_s=req.queue_s, prefill_s=req.prefill_s,
                prefill_bucket=req.prefill_bucket,
                first_decode_s=req.first_decode_s,
                tokens=len(req.tokens) - req.resume_offset,
                preemptions=req.preemptions,
                preempt_wall_s=req.preempt_wall_s or None,
                resume_offset=req.resume_offset,
                ttft_s=req.ttft_s, e2e_s=req.e2e_s,
                error=req.error or "")
            tracing.observe_trace(reg, record)
            reg.emit("obs_trace", record)

    def _finish_slot(self, i: int, reason: str) -> None:
        slot = self._active[i]
        self._active[i] = None
        self._release_pages(i, slot)
        slot.req.finish(reason)
        self._account_finish(slot.req, reason)
        self.registry.gauge("serve_active_slots").set(self.active_slots())

    def _admit(self) -> bool:
        """Admit waiting requests into free slots and prefill them,
        grouped by bucket so each group is one device call. Paged KV:
        admission is FIFO and all-or-nothing per request — when the
        pool cannot cover the next request's prompt, it (and everyone
        behind it) goes back to the queue head until pages free up."""
        import collections
        free = [i for i, s in enumerate(self._active) if s is None]
        if not free:
            return False
        reqs = self.queue.pop_ready(len(free))
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())
        if not reqs:
            return False
        if self._thread_handle is not None:
            # A request can land between the top-of-loop idle beat and
            # this pop; mark busy BEFORE the prefill device call, or a
            # wedged call would hang an officially-idle thread and the
            # thread_stalled watchdog would never fire.
            self._thread_handle.beat("busy")
        admitted = []    # (slot_i, bucket, req, resume, pages, start,
        #                   pinned)
        pending = collections.deque(reqs)
        free_iter = iter(free)
        slot_i = next(free_iter, None)
        while pending and slot_i is not None:
            req = pending[0]
            # Resume-prefill for preempted requests: re-embed the
            # prompt PLUS everything already generated, so the slot
            # picks up exactly where it left off.
            if req.tokens:
                resume = np.concatenate(
                    [req.prompt, np.asarray(req.tokens, np.int32)])
            else:
                resume = req.prompt
            n = int(resume.size)
            try:
                # Conservative full-length fit (cache hits are never
                # guaranteed — eviction must not turn an admissible
                # request into an error later).
                bucket = self.bucket_for(n)
            except PromptTooLongError as e:
                # A resumed request can outgrow the largest prefill
                # bucket; it cannot be re-prefilled — fail it loudly
                # rather than wedge the queue head.
                pending.popleft()
                req.finish(FINISH_ERROR, error=f"preempt-resume: {e}")
                self._account_finish(req, FINISH_ERROR)
                continue
            start = 0
            pinned: List = []
            if self._paged_kv is not None:
                cow_src = None
                if self._prefix is not None:
                    from tpunet.serve.prefixcache import keys as pk
                    # Pin cap (n-1)//page_tokens: at least one suffix
                    # token is always re-prefilled — the logits at
                    # position n-1 come from compute, never from
                    # cached K/V (pages store only K/V rows).
                    pinned = self._prefix.lookup(
                        resume, (n - 1) // self.page_tokens)
                    start = len(pinned) * self.page_tokens
                    if n % self.page_tokens == 0 and pinned \
                            and start == n - self.page_tokens:
                        # Full page-aligned match: the divergence page
                        # is cached too. COW it below instead of
                        # re-prefilling its whole page.
                        cow_src = self._prefix.get(
                            pk.token_prefix_digest(resume, n))
                    # Pin BEFORE allocating: allocation may evict
                    # unpinned cache pages, and the chain (and COW
                    # source) must survive until mapped/copied.
                    if cow_src is not None:
                        self._prefix.pin(pinned + [cow_src])
                    elif pinned:
                        self._prefix.pin(pinned)
                pages = self._alloc_pages_for(slot_i, n,
                                              first_index=len(pinned))
                if pages is None:
                    if cow_src is not None:
                        self._prefix.unpin(pinned + [cow_src])
                    elif pinned:
                        self._prefix.unpin(pinned)
                    break            # pool pressure: FIFO order holds
                # Map the pinned prefix pages into the slot's table
                # (indices 0..k-1): the suffix prefill and every
                # decode step read them through the gather; nothing
                # ever writes them (positions >= start only).
                for j, node in enumerate(pinned):
                    self._page_table[slot_i, j] = node.page
                if cow_src is not None:
                    # Copy-on-write at the divergence page: seed the
                    # private copy from its cached twin, then prefill
                    # only the final token (which overwrites its own
                    # row in the copy — the shared page stays
                    # immutable).
                    self._copy_page(cow_src.page, pages[0])
                    self._prefix.unpin([cow_src])
                    start = n - 1
                    self.registry.counter("serve_prefix_cow_total").inc()
            else:
                pages = []
            pending.popleft()
            if start:
                # The suffix picks the bucket: a 500-token prompt with
                # 480 cached tokens prefills through the 32-bucket
                # program — the TTFT win rides the smaller dispatch.
                bucket = self.bucket_for(n - start)
            admitted.append((slot_i, bucket, req, resume, pages, start,
                             pinned))
            slot_i = next(free_iter, None)
        if pending:
            self.queue.requeue_front(pending)
            self.registry.gauge("serve_queue_depth").set(
                self.queue.depth())
        if not admitted:
            return False
        by_bucket = {}
        for slot_i, bucket, req, resume, pages, start, pinned \
                in admitted:
            by_bucket.setdefault(bucket, []).append(
                (slot_i, req, resume, pages, start, pinned))
        for bucket, group in sorted(by_bucket.items()):
            self._prefill(bucket, group)
        if self._drafter_model is not None:
            # Drafter pool warm-up rides the same admission beat. The
            # drafter re-embeds the FULL prompt (prefix hits included)
            # so the grouping key is the full-length bucket, not the
            # suffix bucket the main prefill used.
            draft_groups: dict = {}
            for slot_i, _, _, resume, _, _, _ in admitted:
                if self._active[slot_i] is None:
                    continue     # finished inside its own prefill
                draft_groups.setdefault(
                    self.bucket_for(int(resume.size)), []).append(
                        (slot_i, resume))
            for bucket, rows in sorted(draft_groups.items()):
                self._draft_prefill(bucket, rows)
        self._update_kv_gauges()
        now_active = self.active_slots()
        self.peak_active_slots = max(self.peak_active_slots, now_active)
        self.registry.gauge("serve_active_slots").set(now_active)
        return True

    def _prefill(self, bucket: int, group) -> None:
        """One chunked-prefill device call for every admitted request
        padded to this bucket; K/V land in each slot's cache rows (or
        pages) and the next token is sampled from the last REAL
        position — on device when the sampler is fused, else from the
        transferred logits row. The padded tail writes garbage K/V
        beyond the prompt — masked invariant: a decode query at
        position p attends only j <= p and overwrites position p
        first, so padding is never visible. ``group`` rows are
        ``(slot_i, req, resume_tokens, pages, start, pinned)``;
        resume_tokens is prompt+generated for a preempted request
        resuming mid-stream, ``start`` is the first position NOT
        covered by pinned prefix-cache pages — only the suffix
        ``resume[start:]`` is embedded, at ``positions = start``, so
        the scatter never touches a pinned page (writes go to
        positions >= start only) while the attend reads the pinned
        K/V through the page table."""
        t0 = time.perf_counter()
        toks = np.zeros((self.slots, bucket), np.int32)
        active = np.zeros((self.slots,), bool)
        last_idx = np.zeros((self.slots,), np.int32)
        positions = np.zeros((self.slots,), np.int32)
        for slot_i, req, resume, pages, start, pinned in group:
            n = int(resume.size)
            toks[slot_i, :n - start] = resume[start:]
            active[slot_i] = True
            last_idx[slot_i] = n - start - 1
            positions[slot_i] = start
            # Slot the request BEFORE the device call: if the step
            # raises, the engine's failure handler finds (and fails)
            # it in _active instead of stranding a popped request.
            self._admit_seq += 1
            slot = _Slot(req, pos=n, next_token=0,
                         generated=len(req.tokens) + 1,
                         seq=self._admit_seq)
            slot.pages = pages
            slot.pinned = pinned
            self._active[slot_i] = slot
        from tpunet.obs import flightrec
        for _, req, resume, _, start, _ in group:
            # A resume-prefill (preempt-resume or cross-replica
            # failover resume) re-embeds prompt+generated; the
            # distinct verb keeps the timeline honest about which
            # prefills are re-work.
            if int(resume.size) > int(req.prompt.size):
                flightrec.record("req", f"resume_prefill {req.id}")
            else:
                flightrec.record("req", f"prefill {req.id}")
            if start:
                flightrec.record(
                    "req", f"prefix_hit {req.id} tokens={start}")
            if req.prefill_start_t is None:
                req.prefill_start_t = t0
                req.prefill_bucket = bucket
            if req._preempt_t is not None:
                req.preempt_wall_s += t0 - req._preempt_t
                req._preempt_t = None
            if req.trace_id:
                tracing.crumb("prefill", req.trace_id, req.trace_hop,
                              rid=req.id, b=bucket)
        if self.chaos is not None:
            self.chaos.on_prefill()     # kill@prefill injection point
        with _ring_span("tpunet/serve_prefill"):
            if self.device_sampling:
                self._cache, sampled = self._dispatch_step(
                    toks, positions, active, last_idx)
                sampled = np.asarray(sampled)
                logits = None
            else:
                self._cache, logits = self._dispatch_step(toks,
                                                          positions,
                                                          active)
                logits = np.asarray(logits)
        reg = self.registry
        # Adopt freshly-written full prompt pages into the prefix
        # cache (and spill them) BEFORE the finish checks below can
        # release a short request's pages.
        if self._prefix is not None:
            for slot_i, req, resume, pages, start, pinned in group:
                slot = self._active[slot_i]
                if slot is not None:
                    self._adopt_prefix_pages(slot_i, slot, resume)
            self._update_kv_gauges()
        prefill_done = time.perf_counter()
        for slot_i, req, resume, _, start, _ in group:
            n = int(resume.size)
            if req.prefill_done_t is None:
                req.prefill_done_t = prefill_done
            if self.device_sampling:
                first = int(sampled[slot_i])
            else:
                first = sample_token(logits[slot_i, n - start - 1],
                                     req)
            fresh = req.first_token_t is None
            self._active[slot_i].next_token = first
            req.push_token(first)
            if fresh:
                flightrec.record("req", f"first_token {req.id}")
                if req.trace_id:
                    tracing.crumb("first_token", req.trace_id,
                                  req.trace_hop, rid=req.id)
                reg.histogram("serve_ttft_s").observe(req.ttft_s)
            reg.counter("serve_tokens_total").inc()
            if self.chaos is not None:
                self.chaos.on_token()   # kill/stall@tokens (post-push:
                #                         the token reached the stream)
            self._slot_maybe_finish(slot_i, first)
        reg.counter("serve_prefills_total").inc()
        # Suffix tokens only: with a prefix hit this is the REAL
        # prefill compute — bench_serve's prefill_tokens_per_request
        # dropping to ~the suffix length is the tentpole's measured
        # win.
        reg.counter("serve_prefill_tokens_total").inc(
            sum(int(r.size) - st for _, _, r, _, st, _ in group))
        reg.histogram("serve_prefill_s").observe(
            time.perf_counter() - t0)

    def _draft_prefill(self, bucket: int, rows) -> None:
        """Prefill the DRAFTER's paged pool for freshly admitted
        slots: one write-only full-prompt pass per bucket. ``rows``
        are ``(slot_i, resume_tokens)``.

        The drafter always embeds the FULL prompt from position 0,
        even when the main prefill rode a prefix-cache hit. Pinned
        prefix page ids are shared across slots and the drafter pool
        mirrors the main page table verbatim, so a drafter write to a
        shared page id is an IDEMPOTENT rewrite: every slot pinning
        that page holds the same token prefix and the drafter is
        deterministic, hence bit-identical K/V. Re-deriving instead
        of caching drafter pages keeps the drafter pool warm with
        ZERO extra allocator state and no drafter-side COW (the
        divergence page's drafter rows are simply written here). The
        cost is one drafter-width full prefill per admission — part
        of the lever's price, measured by ``bench_serve --spec``."""
        toks = np.zeros((self.slots, bucket), np.int32)
        active = np.zeros((self.slots,), bool)
        positions = np.zeros((self.slots,), np.int32)
        for slot_i, resume in rows:
            toks[slot_i, :int(resume.size)] = resume
            active[slot_i] = True
        # Prompt positions span 0..bucket-1, so the attention window
        # is static per bucket — the tag stays ``w{bucket}``.
        win = self._spec_window((bucket - 1) // self.page_tokens + 1)
        with _ring_span("tpunet/serve_spec_prefill"):
            self._draft_cache = self._dispatch_spec(
                "spec_draft_prefill", f"w{bucket}",
                self._draft_prefill_fn,
                (self._drafter_params, self._draft_cache, toks,
                 positions, active, self._page_table[:, :win]))

    def _slot_maybe_finish(self, slot_i: int, token: int) -> bool:
        """Stop checks after a sampled token; True when the slot was
        freed."""
        slot = self._active[slot_i]
        req = slot.req
        if req.stop_token is not None and token == req.stop_token:
            self._finish_slot(slot_i, FINISH_STOP)
            return True
        if slot.generated >= req.max_new_tokens \
                or slot.pos + 1 > self.max_seq_len:
            self._finish_slot(slot_i, FINISH_LENGTH)
            return True
        return False

    def _decode_iteration(self) -> bool:
        """One masked decode step across the whole pool: every active
        slot consumes its pending token at its own position and samples
        the next one (fused on device by default). Paged KV: each
        slot's next write page is allocated here (allocate-on-advance);
        a slot the pool cannot extend sits the iteration out, and when
        NOTHING can advance the youngest blocked slot is preempted back
        to the queue so the others drain and free pages."""
        if self._drafter_model is not None:
            return self._spec_decode_iteration()
        live = [(i, s) for i, s in enumerate(self._active)
                if s is not None]
        if not live:
            return False
        if self._paged_kv is not None:
            ready = []
            blocked = []
            for i, slot in live:
                if self._ensure_page_capacity(i, slot):
                    ready.append((i, slot))
                else:
                    blocked.append((i, slot))
            if blocked and not ready:
                self._preempt_slot(self._choose_preempt_victim(blocked))
                return True          # freed pages; retry next iteration
            self._update_kv_gauges()
            live = ready
            if not live:
                return False
        self._decode_width1(live)
        return True

    def _decode_width1(self, live) -> None:
        """One [slots, 1] masked decode call for ``live`` slots (page
        capacity already ensured by the caller). Shared by the normal
        path and the spec path's tail fallback."""
        t0 = time.perf_counter()
        toks = self._inactive_tok.copy()
        positions = np.zeros((self.slots,), np.int32)
        active = np.zeros((self.slots,), bool)
        for i, slot in live:
            toks[i, 0] = slot.next_token
            positions[i] = slot.pos
            active[i] = True
        with _ring_span("tpunet/serve_decode"):
            if self.device_sampling:
                self._cache, sampled = self._dispatch_step(
                    toks, positions, active, self._zero_idx)
                sampled = np.asarray(sampled)
                logits = None
            else:
                self._cache, logits = self._dispatch_step(toks,
                                                          positions,
                                                          active)
                logits = np.asarray(logits)
        lap = time.perf_counter() - t0
        reg = self.registry
        reg.counter("serve_decode_steps_total").inc()
        reg.histogram("serve_decode_iter_s").observe(lap)
        # per-token latency: the iteration produced one token for each
        # live slot, each of which waited the full iteration.
        reg.histogram("serve_token_s").observe(lap)
        for i, slot in live:
            if self.device_sampling:
                nxt = int(sampled[i])
            else:
                nxt = sample_token(logits[i, 0], slot.req)
            slot.pos += 1
            slot.next_token = nxt
            slot.generated += 1
            slot.req.push_token(nxt)
            reg.counter("serve_tokens_total").inc()
            if self.chaos is not None:
                self.chaos.on_token()   # kill/stall@tokens (post-push)
            self._slot_maybe_finish(i, nxt)

    # -- speculative decode path (docs/serving.md) ----------------------

    def _spec_decode_iteration(self) -> bool:
        """One draft+verify cycle across the pool: burst-eligible
        slots draft K tokens and verify them in one wide call (1..K+1
        verified tokens each); tail slots — too close to max_seq_len
        for a full burst — fall back to the existing width-1 program
        in the same iteration. A slot nearing its TOKEN budget still
        bursts: the emit loop breaks exactly at max_new_tokens (the
        overshot verify positions are wasted compute, and the slot
        releases its pages on finish), which keeps every live slot on
        the wide program instead of serializing request tails into
        width-1 iterations. POOL PRESSURE can also force a width-1
        cycle; such a slot may re-enter the burst later with a
        drafter-pool gap at the width-1-advanced positions. The gap
        costs acceptance (garbage drafter K/V -> bad drafts), never
        correctness: every emitted token comes from the verify (or
        width-1 decode) program, and rejection falls back to one
        verified token per cycle."""
        live = [(i, s) for i, s in enumerate(self._active)
                if s is not None]
        if not live:
            return False
        k = self.spec_k
        burst, seq_ready, blocked = [], [], []
        for i, slot in live:
            eligible = slot.pos + k + 1 <= self.max_seq_len
            # A burst writes pos..pos+K (both pools; shared table) —
            # ensure coverage through pos+K, or fall back to width-1
            # coverage before counting the slot as blocked.
            if eligible and self._ensure_page_capacity(
                    i, slot, through_pos=slot.pos + k):
                burst.append((i, slot))
            elif self._ensure_page_capacity(i, slot):
                seq_ready.append((i, slot))
            else:
                blocked.append((i, slot))
        if blocked and not burst and not seq_ready:
            self._preempt_slot(self._choose_preempt_victim(blocked))
            return True              # freed pages; retry next iteration
        self._update_kv_gauges()
        if not burst and not seq_ready:
            return False
        if burst:
            self._spec_burst(burst)
        if seq_ready:
            # Tail and capacity-starved slots advance one verified
            # token through the plain width-1 program. Their drafter
            # pool now lags the main cursor — benign per the
            # docstring's acceptance-vs-correctness argument.
            self._decode_width1([(i, s) for i, s in seq_ready
                                 if self._active[i] is s])
        return True

    def _spec_burst(self, burst) -> None:
        """Draft K+1, verify K+1, accept, rewind — the spec hot path.
        Acceptance (tpunet/serve/spec.py ``accept_drafts``) keeps the
        longest prefix where draft d_j matched verify choice c_{j-1};
        the slot emits c_0..c_a (ALL from the verify program, which is
        the bitwise spec-off-parity argument), advances its cursor by
        a+1, and the rejected tail pages go back to the free list."""
        k = self.spec_k
        reg = self.registry
        t0 = time.perf_counter()
        first = np.zeros((self.slots,), np.int32)
        positions = np.zeros((self.slots,), np.int32)
        active = np.zeros((self.slots,), bool)
        for i, slot in burst:
            first[i] = slot.next_token
            positions[i] = slot.pos
            active[i] = True
        # Attention window: the smallest page-slot bucket covering
        # every burst slot's pos+K. Both programs see the SLICED
        # table — they attend over (and gather) only the live key
        # window instead of all max_seq_len rows, which is where the
        # verify's per-position cost lives on short sequences.
        win = self._spec_window(
            max(int(s.pos) + k for _, s in burst)
            // self.page_tokens + 1)
        table = self._page_table[:, :win]
        # temp/top_k/top_p/seeds/steps0 — steps0[i] = len(req.tokens)
        # is the sequential sampler's next step counter, so draft and
        # verify keys stay in lockstep with the spec-off stream.
        samp = self._sampling_args(self._zero_idx)[1:]
        with _ring_span("tpunet/serve_spec_draft"):
            self._draft_cache, drafts = self._dispatch_spec(
                "spec_draft_burst", f"k{k}w{win}",
                self._draft_burst_fn,
                (self._drafter_params, self._draft_cache, first,
                 positions, active, table, *samp))
            drafts = np.asarray(drafts)
        verify_toks = np.zeros((self.slots, k + 1), np.int32)
        verify_toks[:, 0] = first
        verify_toks[:, 1:] = drafts
        with _ring_span("tpunet/serve_spec_verify"):
            self._cache, choices = self._dispatch_spec(
                "spec_verify", f"k{k}w{win}", self._verify_fn,
                (self.variables["params"], self._cache, verify_toks,
                 positions, active, table, *samp))
            choices = np.asarray(choices)
        lap = time.perf_counter() - t0
        reg.counter("serve_decode_steps_total").inc()
        reg.histogram("serve_decode_iter_s").observe(lap)
        reg.histogram("serve_token_s").observe(lap)
        from tpunet.serve import spec as serve_spec
        rows = np.asarray([i for i, _ in burst])
        accepted = serve_spec.accept_drafts(drafts[rows],
                                            choices[rows])
        for (i, slot), a in zip(burst, accepted):
            a = int(a)
            reg.counter("serve_spec_draft_tokens_total").inc(k)
            reg.counter("serve_spec_accepted_tokens_total").inc(a)
            reg.counter("serve_spec_rejected_tokens_total").inc(k - a)
            reg.counter("serve_spec_verify_steps_total").inc()
            finished = False
            for j in range(a + 1):
                tok = int(choices[i, j])
                slot.pos += 1
                slot.generated += 1
                slot.next_token = tok
                slot.req.push_token(tok)
                reg.counter("serve_tokens_total").inc()
                if self.chaos is not None:
                    self.chaos.on_token()   # post-push: the token
                    #                         reached the stream —
                    #                         only VERIFIED tokens are
                    #                         ever journaled upstream
                if self._slot_maybe_finish(i, tok):
                    finished = True
                    break
            if not finished:
                self._rewind_slot_pages(i, slot)
        drafted = reg.counter("serve_spec_draft_tokens_total").value
        acc = reg.counter("serve_spec_accepted_tokens_total").value
        reg.gauge("serve_spec_acceptance_rate").set(
            round(acc / drafted, 4) if drafted else 0.0)
        self._update_kv_gauges()

    def _rewind_slot_pages(self, slot_i: int, slot: _Slot) -> None:
        """Cursor rewind after a (partial) rejection: free the private
        tail pages beyond the last verified position. The rows holding
        rejected K/V are simply recycled — the masked write-then-read
        invariant makes stale rows invisible, so the rewind is pure
        host bookkeeping (no device work). Structurally clamped at
        pinned prefix pages: a burst writes only positions >= the
        prefill suffix start, which live on PRIVATE pages, and only
        ``slot.pages`` (the private list) is ever freed — a shared
        prefix page can never be rewound or mutated (pinned either at
        admission COW time or never written at all; pinned by test in
        tests/test_serve_paged.py)."""
        keep_hi = (slot.pos - 1) // self.page_tokens
        keep_private = max(0, keep_hi + 1 - len(slot.pinned))
        tail = slot.pages[keep_private:]
        if not tail:
            return
        del slot.pages[keep_private:]
        base = len(slot.pinned) + keep_private
        for j in range(base, base + len(tail)):
            self._page_table[slot_i, j] = 0
        # reversed(): the page covering the NEXT write position goes
        # back on top of the LIFO free list, so the very next
        # allocate-on-advance hands the same page straight back.
        self._free_pages.extend(reversed(tail))

    # -- obs -------------------------------------------------------------

    def _emit_record(self, final: bool = False) -> None:
        """One ``obs_serve`` record (docs/metrics_schema.md) per window:
        cumulative counters + window histograms, then a fresh window."""
        reg = self.registry
        now = time.perf_counter()
        window = now - self._last_emit
        self._last_emit = now
        record = build_serve_record(
            reg, queue_depth=self.queue.depth(),
            active_slots=self.active_slots(), slots=self.slots,
            uptime_s=now - self._started, window_s=window, final=final)
        if self.chaos is not None:
            # A record from a chaos-armed replica says so: bench and
            # history comparisons must never mistake injected faults
            # for organic regressions.
            record["chaos"] = self.chaos.render()
        # Host-thread gauges ride the serve registry too: GET /metrics
        # and exporters see thread_* ages for the engine loop and any
        # exporter drains.
        from tpunet.obs.flightrec.threads import THREADS
        THREADS.export_gauges(reg)
        reg.emit("obs_serve", record)
        reg.reset_window()
