"""Continuous-batching decode engine over a paged KV pool.

One jitted masked decode step is compiled ONCE for the pool batch
``[slots, 1]`` and amortized across every in-flight request: each
iteration feeds every active slot its next token at its own position
(per-row positions + active mask, tpunet/models/vit.py
``Attention._decode_attend``), so requests join mid-flight and finished
ones free their slot without any recompilation. Prefill runs through
the same masked path as a chunked multi-token call, padded to one of a
fixed set of length buckets — the total compile count is bounded at
``1 + len(prefill_buckets)`` programs for the life of the server.

KV memory is PAGED by default (``ServeConfig.paged_kv``;
``--no-paged-kv`` keeps the dense pool): per layer, K/V live in a
shared pool of ``kv_pages`` pages of ``kv_page_tokens`` tokens each,
addressed through per-slot page tables the engine owns host-side. A
slot costs HBM proportional to its prompt+generated length instead of
``max_seq_len`` — pages are allocated on advance, freed on finish, and
recycled; when the pool is exhausted the YOUNGEST blocked slot is
preempted back to the queue (its progress is kept and resumed by
re-prefilling prompt+generated, token streams never restart). int8
page payloads (``kv_dtype``, per page-row scale, eval-parity-gated)
halve the bf16 page cost again.

Sampling is DEVICE-side by default (``ServeConfig.device_sampling``):
one ``[slots]``-wide batched temperature/top-k/top-p step
(tpunet/serve/sampling.py, per-slot PRNG keys folded per step) is
fused onto the decode program, so only sampled int32 tokens cross the
host boundary — the per-slot host loop (and the ``[slots, V]`` logits
transfer feeding it) leaves the token path. ``sample_token`` below is
the surviving host-side parity reference (and the
``--no-device-sampling`` fallback); greedy output is token-identical
to ``models.lm.generate`` through either sampler (engine parity test).

Obs wiring: SLO counters/gauges/histograms land in a ``tpunet.obs``
``Registry`` (serve_* names incl. the ``serve_kv_*`` page-pool
gauges, docs/metrics_schema.md ``obs_serve``), prefill/decode phases
run under trace spans, and a periodic ``obs_serve`` record is emitted
to every attached sink/exporter.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional

import numpy as np

from tpunet.obs import tracing
from tpunet.serve.scheduler import (FINISH_CANCELLED, FINISH_DEADLINE,
                                    FINISH_DRAIN, FINISH_ERROR,
                                    FINISH_LENGTH, FINISH_STOP,
                                    GenerateRequest, RequestQueue)


class PromptTooLongError(Exception):
    """Prompt exceeds the largest prefill bucket or the KV length."""


@contextlib.contextmanager
def _ring_span(name: str):
    """The serve twin of the trainer's ``_RecordedSpan``: an xprof
    trace span whose begin/end ALSO land in the flight-recorder ring
    (the unified timeline's device phases; the crash tail's "which
    phase was the replica in"). ``span_end`` sits in a finally so a
    raising device call cannot leave a dangling open span for the
    timeline to stretch to the end of the recording."""
    from tpunet.obs import flightrec
    from tpunet.obs.spans import span
    flightrec.record("span", name)
    try:
        with span(name):
            yield
    finally:
        flightrec.record("span_end", name)


def sample_token(logits: np.ndarray, req: GenerateRequest) -> int:
    """Host-side next-token choice from one row of logits [V].

    Greedy (temperature <= 0) is exact argmax. Sampling mirrors
    ``models.lm.filter_logits``: top-k truncation first, then nucleus
    over the renormalized post-top-k distribution; the draw uses the
    request's own seeded numpy Generator (deterministic per request,
    independent across slots).
    """
    if req.temperature <= 0:
        return int(np.argmax(logits))
    lg = logits.astype(np.float64) / req.temperature
    v = lg.shape[-1]
    if req.top_k > 0 and req.top_k < v:
        kth = np.sort(lg)[-req.top_k]
        lg = np.where(lg >= kth, lg, -np.inf)
    if 0.0 < req.top_p < 1.0:
        srt = np.sort(lg)[::-1]
        probs = np.exp(srt - srt.max())
        probs /= probs.sum()
        keep = np.cumsum(probs) - probs < req.top_p
        cutoff = srt[keep].min()
        lg = np.where(lg >= cutoff, lg, -np.inf)
    lg -= lg.max()
    p = np.exp(lg)
    p /= p.sum()
    return int(req.rng().choice(v, p=p))


def build_serve_record(reg, *, queue_depth: int, active_slots: int,
                       slots: int, uptime_s: float, window_s: float,
                       final: bool = False) -> dict:
    """The ``obs_serve`` record body (docs/metrics_schema.md):
    cumulative counters + window histogram summaries. Module-level so
    the schema-conformance check can exercise the exact record shape
    without standing up an engine; the TTFT/e2e histograms also export
    their bounded window sample — the fleet aggregator merges replica
    SLO percentiles from sample points, not from per-replica p99s."""
    record = {
        "uptime_s": round(uptime_s, 3),
        "window_s": round(window_s, 3),
        "queue_depth": queue_depth,
        "active_slots": active_slots,
        "slots": slots,
        "requests_total": int(
            reg.counter("serve_requests_total").value),
        "requests_completed": int(
            reg.counter("serve_requests_completed").value),
        "requests_rejected": int(
            reg.counter("serve_requests_rejected").value),
        "tokens_total": int(reg.counter("serve_tokens_total").value),
        "decode_steps_total": int(
            reg.counter("serve_decode_steps_total").value),
        "prefills_total": int(
            reg.counter("serve_prefills_total").value),
    }
    for name, key in (("serve_ttft_s", "ttft"),
                      ("serve_token_s", "token_latency"),
                      ("serve_e2e_s", "e2e"),
                      ("serve_prefill_s", "prefill")):
        hist = reg.histogram(name)
        summ = hist.summary()
        for stat in ("p50", "p90", "p99", "mean", "count"):
            if stat in summ:
                record[f"{key}_{stat}_s" if stat != "count"
                       else f"{key}_count"] = (
                    round(summ[stat], 6) if stat != "count"
                    else int(summ[stat]))
        if key in ("ttft", "e2e") and summ:
            record[f"{key}_sample"] = [
                round(v, 6) for v in hist.export_sample()]
            if summ.get("approx"):
                record[f"{key}_approx"] = 1
    # Paged-KV pool state (serve_kv_* gauges; zeros on a dense pool):
    # the capacity signal a fleet operator sizes --kv-pages from.
    for gauge_name, field in (("serve_kv_pages_total", "kv_pages_total"),
                              ("serve_kv_pages_used", "kv_pages_used")):
        val = reg.gauge(gauge_name).value
        record[field] = int(val) if val is not None else 0
    bpt = reg.gauge("serve_kv_bytes_per_token").value
    record["kv_bytes_per_token"] = (round(float(bpt), 2)
                                    if bpt is not None else 0)
    if final:
        record["final"] = True
    return record


def build_aot_store(directory: str, model_cfg, serve_cfg):
    """The engine's ``AotProgramStore`` (tpunet/utils/cache.py), keyed
    by every config field that selects a compiled program: the model
    architecture plus the pool shape. A replica booted with a different
    width/depth/slots gets a clean store MISS, never a wrong program
    (the store key additionally folds in jax version + device kind)."""
    import dataclasses

    from tpunet.utils.cache import AotProgramStore

    digest = AotProgramStore.digest({
        "model": dataclasses.asdict(model_cfg),
        "slots": serve_cfg.slots,
        "prefill_buckets": list(serve_cfg.prefill_buckets),
        # The paged-KV + sampling levers each select a DIFFERENT
        # compiled program (pool layout, fused sampler, page dtype):
        # fold them in so flipping a flag is a clean miss, never a
        # stale executable.
        "paged_kv": serve_cfg.paged_kv,
        "kv_pages": serve_cfg.kv_pages,
        "kv_page_tokens": serve_cfg.kv_page_tokens,
        "kv_dtype": serve_cfg.kv_dtype,
        "device_sampling": serve_cfg.device_sampling,
    })
    return AotProgramStore(directory, digest)


class _Slot:
    """Host-side bookkeeping for one KV-cache row."""

    __slots__ = ("req", "pos", "next_token", "generated", "pages",
                 "seq")

    def __init__(self, req: GenerateRequest, pos: int, next_token: int,
                 generated: int = 1, seq: int = 0):
        self.req = req
        self.pos = pos            # next cache write position
        self.next_token = next_token
        self.generated = generated  # tokens produced (resume-aware)
        self.pages: List[int] = []  # paged-KV pages this slot holds
        self.seq = seq            # admission ordinal (preempt youngest)


class Engine:
    """Slot-pool continuous-batching engine for one LM.

    ``model``/``variables`` come from ``infer.generate.load_lm`` (pass
    the same ``mesh`` for tensor-parallel serving — the KV pool is then
    created sharded over the mesh 'model' axis to match the attention's
    head-sharded writes). The engine owns a single background thread;
    ``submit`` is thread-safe and non-blocking (bounded queue).
    """

    def __init__(self, model, variables, cfg, *, registry=None,
                 mesh=None, aot_store=None):
        import jax
        import jax.numpy as jnp

        from tpunet.obs.registry import Registry

        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.mesh = mesh
        self.registry = registry if registry is not None else Registry()
        self.max_seq_len = int(model.max_len)
        self.slots = int(cfg.slots)
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {cfg.slots}")
        self.buckets = tuple(sorted(
            b for b in cfg.prefill_buckets if b <= self.max_seq_len))
        if not self.buckets:
            self.buckets = (self.max_seq_len,)
        self.queue = RequestQueue(cfg.queue_max,
                                  on_finish=self._account_finish)
        self._active: List[Optional[_Slot]] = [None] * self.slots

        # -- paged KV geometry (host-owned allocator) ------------------
        self.device_sampling = bool(cfg.device_sampling)
        self.page_tokens = int(cfg.kv_page_tokens)
        if self.page_tokens < 1:
            raise ValueError(
                f"kv_page_tokens must be >= 1, got {cfg.kv_page_tokens}")
        self.pages_per_slot = -(-self.max_seq_len // self.page_tokens)
        self._paged_kv = None
        if cfg.paged_kv:
            from tpunet.models.vit import PagedKV
            usable = int(cfg.kv_pages) or self.slots * self.pages_per_slot
            if usable < 1:
                raise ValueError(f"kv_pages must be >= 1, got "
                                 f"{cfg.kv_pages}")
            self.kv_pages_usable = usable
            # Free list yields ascending page ids (pop from the end);
            # freed pages re-enter at the end, so recycling is LIFO —
            # a just-freed hot page is the next one handed out.
            self._free_pages = list(range(usable, 0, -1))
            self._page_table = np.zeros(
                (self.slots, self.pages_per_slot), np.int32)
            # pages + 1: page 0 is the reserved garbage page (inactive
            # rows and padded prefill tails write there; the allocator
            # never hands it out).
            self._paged_kv = PagedKV(pages=usable + 1,
                                     page_tokens=self.page_tokens,
                                     dtype=cfg.kv_dtype)
            self._kv_pages_touched: set = set()
        elif cfg.kv_dtype not in ("auto",):
            raise ValueError(
                f"kv_dtype={cfg.kv_dtype!r} requires the paged KV "
                "cache (drop --no-paged-kv or use kv_dtype auto)")
        self._admit_seq = 0
        self.peak_active_slots = 0   # high-water mark (bench_serve
        #                              --slots-sweep admitted-slot count)
        # Serve-tier fault injector (--chaos, tpunet/serve/chaos.py):
        # the engine fires token/prefill/stall hooks, the HTTP
        # frontend the probe/stream ones. None when unarmed.
        from tpunet.serve import chaos as serve_chaos
        self.chaos = serve_chaos.install(getattr(cfg, "chaos", ""))
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._drain_kill = threading.Event()
        self._drained = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_handle = None       # flightrec registry handle
        self.error: Optional[str] = None
        self._last_emit = time.perf_counter()
        self._started = time.perf_counter()

        # -- device programs (compiled lazily, one per shape) ----------
        # One callable; jit specializes per token shape: [N, 1] decode
        # plus one [N, Lb] program per prefill bucket. The cache is
        # donated — it is the engine's single biggest buffer and every
        # call replaces it. With device sampling the batched sampler
        # is FUSED onto the step (the program returns sampled int32
        # tokens, not logits); with paging the per-slot page table
        # rides along as one small int32 input.
        paged_kv = self._paged_kv
        fuse_sampler = self.device_sampling

        def _masked_step(params, cache, tokens, positions, active,
                         *extra):
            i = 0
            page_table = None
            if paged_kv is not None:
                page_table = extra[i]
                i += 1
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tokens, train=False,
                decode=True, pos_offset=positions, decode_active=active,
                paged_kv=paged_kv, page_table=page_table,
                mutable=["cache"])
            if not fuse_sampler:
                return mutated["cache"], logits
            from tpunet.serve.sampling import batched_sample
            last_idx, temp, top_k, top_p, seeds, steps = extra[i:i + 6]
            rows = jnp.take_along_axis(
                logits, last_idx[:, None, None],
                axis=1)[:, 0].astype(jnp.float32)
            toks = batched_sample(rows, temp, top_k, top_p, seeds,
                                  steps)
            return mutated["cache"], toks

        self._step = jax.jit(_masked_step, donate_argnums=(1,))
        self._cache = self._make_cache()
        self._inactive_tok = np.zeros((self.slots, 1), np.int32)
        self._zero_idx = np.zeros((self.slots,), np.int32)
        self._init_kv_gauges()
        # AOT warm-start (tpunet/utils/cache.py AotProgramStore): the
        # engine's program set is closed — [N, 1] decode + one [N, Lb]
        # per bucket — so fully-compiled executables deserialize at
        # boot and the jit path above becomes the fallback for shapes
        # the store has never seen. Single-device only: a sharded pool
        # would bake device assignments into the executable.
        self._aot: dict = {}
        self.aot_status: dict = {}
        if aot_store is not None and mesh is None:
            self._warm_start_aot(aot_store)

    def _warm_start_aot(self, store) -> None:
        """Load (or compile-and-save) every program the pool can run.
        Deserialization skips tracing/lowering/XLA entirely — the
        compile-bound replica cold-start becomes an mmap + relink."""
        import jax

        def sds(tree):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

        params_s = sds(self.variables["params"])
        cache_s = sds(self._cache)
        i32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.int32)  # noqa: E731
        f32 = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731
        pos_s = i32(self.slots)
        act_s = jax.ShapeDtypeStruct((self.slots,), bool)
        extra_s = []
        if self._paged_kv is not None:
            extra_s.append(i32(self.slots, self.pages_per_slot))
        if self.device_sampling:
            extra_s += [i32(self.slots), f32(self.slots),
                        i32(self.slots), f32(self.slots),
                        i32(self.slots), i32(self.slots)]
        for width in (1,) + self.buckets:
            tag = f"w{width}"
            toks_s = jax.ShapeDtypeStruct((self.slots, width), np.int32)
            program = store.load("masked_step", tag)
            if program is None:
                # Compile fresh (persistent compile cache off): a
                # cache-served executable saves a poison blob that
                # fails to deserialize at the next boot.
                from tpunet.utils.cache import serializable_compile
                with serializable_compile():
                    program = self._step.lower(
                        params_s, cache_s, toks_s, pos_s, act_s,
                        *extra_s).compile()
                saved = store.save("masked_step", tag, program)
                self.aot_status[tag] = ("compiled+saved" if saved
                                        else "compiled")
            else:
                self.aot_status[tag] = "loaded"
            self._aot[width] = program

    def _dispatch_step(self, toks, positions, active, last_idx=None):
        """Run one masked-step program: the AOT executable for this
        token width when warm-started, the jit fallback otherwise.
        Returns (cache, logits) host-sampling, (cache, tokens) with
        the fused device sampler."""
        program = self._aot.get(toks.shape[1])
        if program is None:
            program = self._step
        args = [self.variables["params"], self._cache, toks, positions,
                active]
        if self._paged_kv is not None:
            args.append(self._page_table)
        if self.device_sampling:
            args.extend(self._sampling_args(
                last_idx if last_idx is not None else self._zero_idx))
        return program(*args)

    def _sampling_args(self, last_idx):
        """Per-slot sampling parameters for the fused device sampler:
        temperature/top-k/top-p/seed from each resident request, plus
        each slot's generated-token count (the per-step key fold — a
        preempted-and-resumed request continues its exact sample
        stream)."""
        n = self.slots
        temp = np.zeros(n, np.float32)
        top_k = np.zeros(n, np.int32)
        top_p = np.zeros(n, np.float32)
        seeds = np.zeros(n, np.int32)
        steps = np.zeros(n, np.int32)
        for i, slot in enumerate(self._active):
            if slot is None:
                continue
            r = slot.req
            temp[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            seeds[i] = r.seed    # admission-validated into [0, 2**31)
            steps[i] = len(r.tokens)
        return [np.asarray(last_idx, np.int32), temp, top_k, top_p,
                seeds, steps]

    # -- pool construction ---------------------------------------------

    def _make_cache(self):
        import jax
        import jax.numpy as jnp
        init_kw = {}
        if self._paged_kv is not None:
            init_kw = dict(
                paged_kv=self._paged_kv,
                page_table=jnp.zeros((self.slots, self.pages_per_slot),
                                     jnp.int32))
        shapes = jax.eval_shape(
            lambda: self.model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((self.slots, self.max_seq_len), jnp.int32),
                decode=True, **init_kw))

        def zeros(s):
            if self.mesh is not None:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                tp = self.mesh.shape.get("model", 1)
                if s.ndim == 4 and tp > 1 and s.shape[2] % tp == 0:
                    spec = P(None, None, "model", None)   # dense pool
                elif s.ndim == 3 and tp > 1 and s.shape[1] % tp == 0:
                    spec = P(None, "model", None)         # page pool
                else:
                    spec = P()
                return jnp.zeros(s.shape, s.dtype,
                                 device=NamedSharding(self.mesh, spec))
            return jnp.zeros(s.shape, s.dtype)

        return jax.tree_util.tree_map(zeros, shapes["cache"])

    def kv_pool_bytes(self) -> int:
        """Resident bytes of the KV cache tree (page pool + scales
        when paged; the dense [slots, max_seq_len] pool otherwise) —
        the capacity number ``bench_serve.py`` reports per slot."""
        import jax
        return int(sum(leaf.nbytes
                       for leaf in jax.tree_util.tree_leaves(
                           self._cache)))

    def kv_bytes_per_token(self) -> float:
        """KV bytes pinned per cacheable token position across the
        whole pool (pages incl. scale sidecars / dense rows)."""
        if self._paged_kv is not None:
            rows = self._paged_kv.pages * self.page_tokens
        else:
            rows = self.slots * self.max_seq_len
        return self.kv_pool_bytes() / max(1, rows)

    def _init_kv_gauges(self) -> None:
        reg = self.registry
        reg.gauge("serve_kv_bytes_per_token").set(
            round(self.kv_bytes_per_token(), 2))
        if self._paged_kv is not None:
            reg.gauge("serve_kv_pages_total").set(self.kv_pages_usable)
            reg.gauge("serve_kv_pages_used").set(0)

    def _update_kv_gauges(self) -> None:
        if self._paged_kv is not None:
            self.registry.gauge("serve_kv_pages_used").set(
                self.kv_pages_usable - len(self._free_pages))

    # -- paged-KV page allocator (engine thread only) -------------------

    def _alloc_pages_for(self, slot_i: int, n_tokens: int):
        """Allocate pages covering ``n_tokens`` prefill positions for
        an admission; None when the pool cannot cover it right now
        (the request stays queued). All-or-nothing."""
        need = -(-n_tokens // self.page_tokens)
        if len(self._free_pages) < need:
            return None
        pages = [self._free_pages.pop() for _ in range(need)]
        for j, p in enumerate(pages):
            self._page_table[slot_i, j] = p
        self._kv_pages_touched.update(pages)
        self.registry.counter("serve_kv_page_allocs_total").inc(need)
        return pages

    def _ensure_page_capacity(self, slot_i: int, slot: _Slot) -> bool:
        """Allocate-on-advance: make sure the page covering the slot's
        next write position exists. False = pool exhausted (the slot
        sits this iteration out, or gets preempted)."""
        need = slot.pos // self.page_tokens + 1
        while len(slot.pages) < need:
            if not self._free_pages:
                return False
            p = self._free_pages.pop()
            self._page_table[slot_i, len(slot.pages)] = p
            slot.pages.append(p)
            self._kv_pages_touched.add(p)
            self.registry.counter("serve_kv_page_allocs_total").inc()
        return True

    def _release_pages(self, slot_i: int, slot: _Slot) -> None:
        """Free-on-finish with recycling: the slot's pages re-enter
        the free list (LIFO) and its table row resets to the garbage
        page."""
        if self._paged_kv is None:
            return
        self._free_pages.extend(slot.pages)
        slot.pages = []
        self._page_table[slot_i, :] = 0
        self._update_kv_gauges()

    def _choose_preempt_victim(self, blocked) -> int:
        """Pick the slot index to preempt from ``blocked``
        [(slot_i, slot), ...]: the YOUNGEST admission whose resume
        prefill (prompt + generated) still fits a bucket. Preempting
        an unresumable slot turns transient pool pressure into a
        client-visible error, so one is chosen only when every
        blocked slot is unresumable (then the youngest fails —
        unavoidable, but never a healthy request while a resumable
        victim exists). Oldest-resumable-survives keeps forward
        progress: the surviving residents eventually finish and free
        pages."""
        largest = self.buckets[-1]
        resumable = [it for it in blocked
                     if it[1].req.prompt.size
                     + len(it[1].req.tokens) <= largest]
        pool = resumable if resumable else blocked
        return max(pool, key=lambda it: it[1].seq)[0]

    def _preempt_slot(self, slot_i: int) -> None:
        """Pool exhausted and nothing can advance: push the youngest
        blocked request back to the HEAD of the queue with its
        progress intact (tokens already streamed stay valid; on
        re-admission the engine re-prefills prompt+generated and the
        sample stream continues at its per-step key fold)."""
        slot = self._active[slot_i]
        self._active[slot_i] = None
        self._release_pages(slot_i, slot)
        req = slot.req
        req.preemptions += 1
        req._preempt_t = time.perf_counter()
        self.registry.counter("serve_kv_preemptions_total").inc()
        from tpunet.obs import flightrec
        flightrec.record("req", f"preempt {req.id}")
        if req.trace_id:
            tracing.crumb("preempt", req.trace_id, req.trace_hop,
                          rid=req.id)
        self.queue.requeue_front([req])
        self.registry.gauge("serve_active_slots").set(
            self.active_slots())
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())

    # -- public API ------------------------------------------------------

    def start(self) -> "Engine":
        # Host-thread registry (tpunet/obs/flightrec/): a decode
        # iteration wedged on the device past the budget pages
        # thread_stalled; idle waits (empty pool) do not.
        from tpunet.obs import flightrec
        self._thread_handle = flightrec.register_thread(
            "serve-engine", stall_after_s=120.0)
        flightrec.record("serve", f"engine start slots={self.slots}")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpunet-serve-engine")
        self._thread.start()
        return self

    @property
    def healthy(self) -> bool:
        return (self.error is None and self._thread is not None
                and self._thread.is_alive())

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def active_slots(self) -> int:
        return sum(1 for s in self._active if s is not None)

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLongError(
            f"prompt of {prompt_len} tokens exceeds the largest "
            f"prefill bucket ({self.buckets[-1]})")

    def submit(self, prompt, **kw) -> GenerateRequest:
        """Admit a request (or raise QueueFullError / DrainingError /
        PromptTooLongError / ValueError). The generation budget is
        clamped to the operator cap and the KV length, but never
        silently: ``req.requested_max_new_tokens`` keeps what the
        client asked for, ``req.max_new_tokens`` is the EFFECTIVE
        budget the frontend reports back. Never blocks."""
        if self.error is not None:
            from tpunet.serve.scheduler import DrainingError
            raise DrainingError(f"engine failed: {self.error}")
        kw.setdefault("max_new_tokens", self.cfg.default_max_new_tokens)
        requested = int(kw["max_new_tokens"])
        kw["max_new_tokens"] = min(requested,
                                   self.cfg.max_new_tokens_cap)
        if (kw.get("deadline_s") or 0) <= 0 \
                and self.cfg.default_deadline_s > 0:
            kw["deadline_s"] = self.cfg.default_deadline_s
        req = GenerateRequest(prompt, **kw)
        req.requested_max_new_tokens = requested
        try:
            n = int(req.prompt.size)
            # A cross-replica resume (router failover) re-prefills
            # prompt PLUS the journaled tokens: the combined length
            # must fit a bucket, like any preempt-resume.
            self.bucket_for(n + req.resume_offset)
            if n + req.max_new_tokens > self.max_seq_len:
                req.max_new_tokens = self.max_seq_len - n
                if req.max_new_tokens < 1:
                    raise PromptTooLongError(
                        f"prompt of {n} tokens leaves no room to "
                        f"generate (max_seq_len {self.max_seq_len})")
            if self._paged_kv is not None:
                # Completability guard: a request whose FULL length
                # cannot fit the page pool even alone would preempt
                # itself forever — reject it up front instead.
                worst = -(-(n + req.max_new_tokens) // self.page_tokens)
                if worst > self.kv_pages_usable:
                    raise PromptTooLongError(
                        f"request needs {worst} KV pages at full "
                        f"length but the pool has "
                        f"{self.kv_pages_usable}; lower "
                        "max_new_tokens or grow --kv-pages")
            if req.resume_offset and req.temperature > 0 \
                    and not self.device_sampling:
                # The sampled-continuation determinism guarantee rests
                # on the device sampler's counter-based (seed, step)
                # keys. The host sampler draws from a STATEFUL
                # generator — a resume would restart it at draw 0 and
                # diverge from the uninterrupted stream. Reject loudly
                # (the router degrades to the honest error frame)
                # rather than continue wrong.
                raise ValueError(
                    "sampled resume_tokens require device-side "
                    "sampling (counter-based per-(seed, step) keys); "
                    "this replica runs --no-device-sampling")
            if req.resume_offset and req.stop_token is not None \
                    and req.stop_token in req.tokens:
                # The journal already contains the stop token: the
                # donor died between streaming it and the done frame.
                # An uninterrupted run stops THERE — finish as 'stop'
                # without a slot, never generate past it.
                req.finish(FINISH_STOP)
                self._account_finish(req, FINISH_STOP)
                self.registry.counter("serve_requests_total").inc()
                return req
            if req.resume_offset \
                    and req.resume_offset >= req.max_new_tokens:
                # Mid-stream-failover resume whose journal already
                # meets the (possibly clamped) budget: the donor
                # replica died between its last token and the done
                # frame. Nothing to decode — finish as length without
                # ever taking a slot.
                req.finish(FINISH_LENGTH)
                self._account_finish(req, FINISH_LENGTH)
                self.registry.counter("serve_requests_total").inc()
                return req
            self.queue.submit(req)       # may raise QueueFull/Draining
        except Exception:
            self.registry.counter("serve_requests_rejected").inc()
            raise
        # Request-lifecycle breadcrumb into the flight-recorder ring:
        # submit -> prefill -> first_token -> finish become the
        # queue/prefill/decode phases on the unified timeline
        # (tpunet/obs/history/timeline.py). ~1-2 us each, no-op
        # without an armed recorder.
        from tpunet.obs import flightrec
        flightrec.record("req", f"submit {req.id} len={req.prompt.size}")
        if req.resume_offset:
            # Cross-replica resume (router failover): without this
            # mark the request's second half starts with a bare
            # prefill and the timeline can't tell a resumed stream
            # from a fresh one.
            flightrec.record(
                "req", f"resume {req.id} off={req.resume_offset}")
        if req.trace_id:
            tracing.crumb("submit", req.trace_id, req.trace_hop,
                          rid=req.id)
        self.registry.counter("serve_requests_total").inc()
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())
        self._wake.set()
        return req

    def _kill_survivors(self, reason: str) -> None:
        """Finish every in-flight and still-queued request with
        ``reason``, through the shared accounting. Only safe from the
        engine thread, or once it can no longer run."""
        for i, slot in enumerate(self._active):
            if slot is not None:
                self._finish_slot(i, reason)
        while True:
            reqs = self.queue.pop_ready(self.queue.queue_max)
            if not reqs:
                break
            for req in reqs:
                req.finish(reason)
                self._account_finish(req, reason)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting, let in-flight (and
        already-queued) requests finish, then stop the loop. Returns
        True when everything finished inside the timeout; leftovers are
        cancelled with finish_reason='drain'."""
        self._draining.set()
        waiting = self.queue.close()
        self._wake.set()
        if self._thread is None or not self._thread.is_alive():
            # Never started (or already dead): there is no loop to
            # finish the work — fail fast instead of waiting a budget
            # that can never be met.
            clean = self.active_slots() == 0 and not waiting
            self._kill_survivors(FINISH_DRAIN)
            self._stop.set()
            self._drained.set()
            return clean
        budget = timeout if timeout is not None \
            else self.cfg.drain_timeout_s
        clean = self._drained.wait(budget)
        if not clean:
            # Timeout: the ENGINE finishes survivors (in-flight and
            # still-queued alike) with reason 'drain' — through
            # _finish_slot so the serve_finished_drain counters and
            # e2e accounting stay truthful, and distinguishable from a
            # client-initiated cancel.
            self._drain_kill.set()
            self._wake.set()
            self._drained.wait(5.0)
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        return clean

    def stop(self) -> None:
        """Hard stop (tests / error paths): cancel everything. Unlike
        cancel() alone, every in-flight request is FINISHED here —
        clients blocked in result()/events() must unblock now, not at
        their own timeout."""
        self._draining.set()
        self.queue.fail_all("engine stopped")
        for slot in list(self._active):
            if slot is not None:
                slot.req.cancel()
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        # The loop exits at the top of its while without a final reap:
        # finish whatever it left behind (thread joined or never ran,
        # so this is single-threaded now).
        self._kill_survivors(FINISH_CANCELLED)

    # -- engine loop -----------------------------------------------------

    def _run(self) -> None:
        from tpunet.obs import flightrec
        handle = self._thread_handle
        try:
            while not self._stop.is_set():
                # Claim busy only when there is (potential) work: an
                # empty iteration is a poll, not work, and marking it
                # busy would (a) lie to the thread_stalled watchdog
                # and (b) flood the flight-recorder ring with ~100
                # busy/idle transition events per second from an idle
                # server, evicting the request breadcrumbs the
                # timeline exporter needs. A wedged device call always
                # had work, so stall detection is unaffected.
                if (self.active_slots() or self.queue.depth()
                        or self._drain_kill.is_set()):
                    handle.beat("busy")
                else:
                    handle.beat("idle")
                did_work = self._iterate()
                if self._draining.is_set() and self.active_slots() == 0 \
                        and self.queue.depth() == 0:
                    break
                if not did_work:
                    handle.beat("idle")
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
            handle.beat("idle")
            self._emit_record(final=True)
        except BaseException as e:  # noqa: BLE001 — engine death is a
            # liveness event: surface through /healthz and fail every
            # request fast rather than hanging clients.
            self.error = f"{type(e).__name__}: {e}"
            flightrec.record("serve", f"engine error: {e}")
            for slot in self._active:
                if slot is not None:
                    slot.req.finish(FINISH_ERROR, error=self.error)
            self._active = [None] * self.slots
            self.queue.fail_all(self.error)
        finally:
            self._drained.set()

    def _iterate(self) -> bool:
        """One engine iteration: reap -> admit(prefill) -> decode.
        Returns False when there was nothing to do (caller sleeps)."""
        if self._drain_kill.is_set():
            # Drain timeout expired: everything still alive finishes
            # with reason 'drain' (the shutdown took it, not a client).
            self._kill_survivors(FINISH_DRAIN)
            return False
        if self.chaos is not None:
            self.chaos.maybe_stall()    # wedged-replica injection
        self._reap()
        admitted = self._admit()
        stepped = self._decode_iteration()
        now = time.perf_counter()
        if self.cfg.emit_every_s > 0 \
                and now - self._last_emit >= self.cfg.emit_every_s:
            self._emit_record()
        return admitted or stepped

    def _reap(self) -> None:
        """Free slots whose request was cancelled or hit its deadline
        (cooperative cancellation point)."""
        now = time.perf_counter()
        for i, slot in enumerate(self._active):
            if slot is None:
                continue
            if slot.req.cancelled:
                self._finish_slot(i, FINISH_CANCELLED)
            elif slot.req.expired(now):
                self._finish_slot(i, FINISH_DEADLINE)

    def _account_finish(self, req, reason: str) -> None:
        """Finish accounting shared by slot-finishes and requests the
        QUEUE finishes before they ever reach a slot: the counters must
        reconcile (requests_total == rejected + sum(finished_*))."""
        reg = self.registry
        from tpunet.obs import flightrec
        flightrec.record("req", f"finish {req.id} {reason}")
        reg.counter(f"serve_finished_{reason}").inc()
        if reason in (FINISH_LENGTH, FINISH_STOP):
            reg.counter("serve_requests_completed").inc()
        if req.e2e_s is not None:
            reg.histogram("serve_e2e_s").observe(req.e2e_s)
        if req.trace_id:
            # Close this hop's replica span: crumb for the timeline
            # join, one obs_trace record with the phase decomposition
            # for the fleet rollup. The empty-trace_id check above is
            # the whole cost on the unsampled path.
            tracing.crumb("finish", req.trace_id, req.trace_hop,
                          rid=req.id, reason=reason)
            record = tracing.build_trace_record(
                trace_id=req.trace_id, hop=req.trace_hop,
                role="replica", finish_reason=reason,
                queue_s=req.queue_s, prefill_s=req.prefill_s,
                prefill_bucket=req.prefill_bucket,
                first_decode_s=req.first_decode_s,
                tokens=len(req.tokens) - req.resume_offset,
                preemptions=req.preemptions,
                preempt_wall_s=req.preempt_wall_s or None,
                resume_offset=req.resume_offset,
                ttft_s=req.ttft_s, e2e_s=req.e2e_s,
                error=req.error or "")
            tracing.observe_trace(reg, record)
            reg.emit("obs_trace", record)

    def _finish_slot(self, i: int, reason: str) -> None:
        slot = self._active[i]
        self._active[i] = None
        self._release_pages(i, slot)
        slot.req.finish(reason)
        self._account_finish(slot.req, reason)
        self.registry.gauge("serve_active_slots").set(self.active_slots())

    def _admit(self) -> bool:
        """Admit waiting requests into free slots and prefill them,
        grouped by bucket so each group is one device call. Paged KV:
        admission is FIFO and all-or-nothing per request — when the
        pool cannot cover the next request's prompt, it (and everyone
        behind it) goes back to the queue head until pages free up."""
        import collections
        free = [i for i, s in enumerate(self._active) if s is None]
        if not free:
            return False
        reqs = self.queue.pop_ready(len(free))
        self.registry.gauge("serve_queue_depth").set(self.queue.depth())
        if not reqs:
            return False
        if self._thread_handle is not None:
            # A request can land between the top-of-loop idle beat and
            # this pop; mark busy BEFORE the prefill device call, or a
            # wedged call would hang an officially-idle thread and the
            # thread_stalled watchdog would never fire.
            self._thread_handle.beat("busy")
        admitted = []        # (slot_i, bucket, req, resume_tokens)
        pending = collections.deque(reqs)
        free_iter = iter(free)
        slot_i = next(free_iter, None)
        while pending and slot_i is not None:
            req = pending[0]
            # Resume-prefill for preempted requests: re-embed the
            # prompt PLUS everything already generated, so the slot
            # picks up exactly where it left off.
            if req.tokens:
                resume = np.concatenate(
                    [req.prompt, np.asarray(req.tokens, np.int32)])
            else:
                resume = req.prompt
            try:
                bucket = self.bucket_for(int(resume.size))
            except PromptTooLongError as e:
                # A resumed request can outgrow the largest prefill
                # bucket; it cannot be re-prefilled — fail it loudly
                # rather than wedge the queue head.
                pending.popleft()
                req.finish(FINISH_ERROR, error=f"preempt-resume: {e}")
                self._account_finish(req, FINISH_ERROR)
                continue
            if self._paged_kv is not None:
                pages = self._alloc_pages_for(slot_i, int(resume.size))
                if pages is None:
                    break            # pool pressure: FIFO order holds
            else:
                pages = []
            pending.popleft()
            admitted.append((slot_i, bucket, req, resume, pages))
            slot_i = next(free_iter, None)
        if pending:
            self.queue.requeue_front(pending)
            self.registry.gauge("serve_queue_depth").set(
                self.queue.depth())
        if not admitted:
            return False
        by_bucket = {}
        for slot_i, bucket, req, resume, pages in admitted:
            by_bucket.setdefault(bucket, []).append(
                (slot_i, req, resume, pages))
        for bucket, group in sorted(by_bucket.items()):
            self._prefill(bucket, group)
        self._update_kv_gauges()
        now_active = self.active_slots()
        self.peak_active_slots = max(self.peak_active_slots, now_active)
        self.registry.gauge("serve_active_slots").set(now_active)
        return True

    def _prefill(self, bucket: int, group) -> None:
        """One chunked-prefill device call for every admitted request
        padded to this bucket; K/V land in each slot's cache rows (or
        pages) and the next token is sampled from the last REAL
        position — on device when the sampler is fused, else from the
        transferred logits row. The padded tail writes garbage K/V
        beyond the prompt — masked invariant: a decode query at
        position p attends only j <= p and overwrites position p
        first, so padding is never visible. ``group`` rows are
        ``(slot_i, req, resume_tokens, pages)``; resume_tokens is
        prompt+generated for a preempted request resuming mid-stream.
        """
        t0 = time.perf_counter()
        toks = np.zeros((self.slots, bucket), np.int32)
        active = np.zeros((self.slots,), bool)
        last_idx = np.zeros((self.slots,), np.int32)
        for slot_i, req, resume, pages in group:
            n = int(resume.size)
            toks[slot_i, :n] = resume
            active[slot_i] = True
            last_idx[slot_i] = n - 1
            # Slot the request BEFORE the device call: if the step
            # raises, the engine's failure handler finds (and fails)
            # it in _active instead of stranding a popped request.
            self._admit_seq += 1
            slot = _Slot(req, pos=n, next_token=0,
                         generated=len(req.tokens) + 1,
                         seq=self._admit_seq)
            slot.pages = pages
            self._active[slot_i] = slot
        positions = np.zeros((self.slots,), np.int32)
        from tpunet.obs import flightrec
        for _, req, resume, _ in group:
            # A resume-prefill (preempt-resume or cross-replica
            # failover resume) re-embeds prompt+generated; the
            # distinct verb keeps the timeline honest about which
            # prefills are re-work.
            if int(resume.size) > int(req.prompt.size):
                flightrec.record("req", f"resume_prefill {req.id}")
            else:
                flightrec.record("req", f"prefill {req.id}")
            if req.prefill_start_t is None:
                req.prefill_start_t = t0
                req.prefill_bucket = bucket
            if req._preempt_t is not None:
                req.preempt_wall_s += t0 - req._preempt_t
                req._preempt_t = None
            if req.trace_id:
                tracing.crumb("prefill", req.trace_id, req.trace_hop,
                              rid=req.id, b=bucket)
        if self.chaos is not None:
            self.chaos.on_prefill()     # kill@prefill injection point
        with _ring_span("tpunet/serve_prefill"):
            if self.device_sampling:
                self._cache, sampled = self._dispatch_step(
                    toks, positions, active, last_idx)
                sampled = np.asarray(sampled)
                logits = None
            else:
                self._cache, logits = self._dispatch_step(toks,
                                                          positions,
                                                          active)
                logits = np.asarray(logits)
        reg = self.registry
        prefill_done = time.perf_counter()
        for slot_i, req, resume, _ in group:
            n = int(resume.size)
            if req.prefill_done_t is None:
                req.prefill_done_t = prefill_done
            if self.device_sampling:
                first = int(sampled[slot_i])
            else:
                first = sample_token(logits[slot_i, n - 1], req)
            fresh = req.first_token_t is None
            self._active[slot_i].next_token = first
            req.push_token(first)
            if fresh:
                flightrec.record("req", f"first_token {req.id}")
                if req.trace_id:
                    tracing.crumb("first_token", req.trace_id,
                                  req.trace_hop, rid=req.id)
                reg.histogram("serve_ttft_s").observe(req.ttft_s)
            reg.counter("serve_tokens_total").inc()
            if self.chaos is not None:
                self.chaos.on_token()   # kill/stall@tokens (post-push:
                #                         the token reached the stream)
            self._slot_maybe_finish(slot_i, first)
        reg.counter("serve_prefills_total").inc()
        reg.counter("serve_prefill_tokens_total").inc(
            sum(int(r.size) for _, _, r, _ in group))
        reg.histogram("serve_prefill_s").observe(
            time.perf_counter() - t0)

    def _slot_maybe_finish(self, slot_i: int, token: int) -> bool:
        """Stop checks after a sampled token; True when the slot was
        freed."""
        slot = self._active[slot_i]
        req = slot.req
        if req.stop_token is not None and token == req.stop_token:
            self._finish_slot(slot_i, FINISH_STOP)
            return True
        if slot.generated >= req.max_new_tokens \
                or slot.pos + 1 > self.max_seq_len:
            self._finish_slot(slot_i, FINISH_LENGTH)
            return True
        return False

    def _decode_iteration(self) -> bool:
        """One masked decode step across the whole pool: every active
        slot consumes its pending token at its own position and samples
        the next one (fused on device by default). Paged KV: each
        slot's next write page is allocated here (allocate-on-advance);
        a slot the pool cannot extend sits the iteration out, and when
        NOTHING can advance the youngest blocked slot is preempted back
        to the queue so the others drain and free pages."""
        live = [(i, s) for i, s in enumerate(self._active)
                if s is not None]
        if not live:
            return False
        if self._paged_kv is not None:
            ready = []
            blocked = []
            for i, slot in live:
                if self._ensure_page_capacity(i, slot):
                    ready.append((i, slot))
                else:
                    blocked.append((i, slot))
            if blocked and not ready:
                self._preempt_slot(self._choose_preempt_victim(blocked))
                return True          # freed pages; retry next iteration
            self._update_kv_gauges()
            live = ready
            if not live:
                return False
        t0 = time.perf_counter()
        toks = self._inactive_tok.copy()
        positions = np.zeros((self.slots,), np.int32)
        active = np.zeros((self.slots,), bool)
        for i, slot in live:
            toks[i, 0] = slot.next_token
            positions[i] = slot.pos
            active[i] = True
        with _ring_span("tpunet/serve_decode"):
            if self.device_sampling:
                self._cache, sampled = self._dispatch_step(
                    toks, positions, active, self._zero_idx)
                sampled = np.asarray(sampled)
                logits = None
            else:
                self._cache, logits = self._dispatch_step(toks,
                                                          positions,
                                                          active)
                logits = np.asarray(logits)
        lap = time.perf_counter() - t0
        reg = self.registry
        reg.counter("serve_decode_steps_total").inc()
        reg.histogram("serve_decode_iter_s").observe(lap)
        # per-token latency: the iteration produced one token for each
        # live slot, each of which waited the full iteration.
        reg.histogram("serve_token_s").observe(lap)
        for i, slot in live:
            if self.device_sampling:
                nxt = int(sampled[i])
            else:
                nxt = sample_token(logits[i, 0], slot.req)
            slot.pos += 1
            slot.next_token = nxt
            slot.generated += 1
            slot.req.push_token(nxt)
            reg.counter("serve_tokens_total").inc()
            if self.chaos is not None:
                self.chaos.on_token()   # kill/stall@tokens (post-push)
            self._slot_maybe_finish(i, nxt)
        return True

    # -- obs -------------------------------------------------------------

    def _emit_record(self, final: bool = False) -> None:
        """One ``obs_serve`` record (docs/metrics_schema.md) per window:
        cumulative counters + window histograms, then a fresh window."""
        reg = self.registry
        now = time.perf_counter()
        window = now - self._last_emit
        self._last_emit = now
        record = build_serve_record(
            reg, queue_depth=self.queue.depth(),
            active_slots=self.active_slots(), slots=self.slots,
            uptime_s=now - self._started, window_s=window, final=final)
        if self.chaos is not None:
            # A record from a chaos-armed replica says so: bench and
            # history comparisons must never mistake injected faults
            # for organic regressions.
            record["chaos"] = self.chaos.render()
        # Host-thread gauges ride the serve registry too: GET /metrics
        # and exporters see thread_* ages for the engine loop and any
        # exporter drains.
        from tpunet.obs.flightrec.threads import THREADS
        THREADS.export_gauges(reg)
        reg.emit("obs_serve", record)
        reg.reset_window()
