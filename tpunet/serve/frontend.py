"""Stdlib-only threaded HTTP frontend for the serving engine.

Endpoints:

- ``POST /v1/generate`` — body ``{"prompt": "text"}`` (byte-level
  vocab-256 checkpoints) or ``{"tokens": [ids]}``, plus optional
  ``max_new_tokens``, ``temperature``, ``top_k``, ``top_p``, ``seed``,
  ``deadline_s``, ``stop_token``, ``stream``. Non-streaming returns one
  JSON object; ``"stream": true`` returns ndjson token events
  (``{"token": id, "text": "..."}`` per line, then a final
  ``{"done": true, ...}`` line) flushed as they are produced.
- ``POST /v1/classify`` — ``{"image": [[[u8,..]]]}`` nested HWC list
  (or ``{"image_b64": "...", "shape": [H, W, 3]}`` raw RGB bytes),
  optional ``topk``; micro-batched across concurrent requests.
- ``GET /healthz`` — 200 while the engine loop is alive and admitting;
  503 (with the error) once the engine thread died or the server is
  draining — an orchestrator restarts the pod instead of watching a
  silent hang.
- ``GET /metrics`` — flat JSON snapshot of the serve registry
  (counters, gauges, histogram percentiles).

Backpressure maps to status codes: 429 queue-full, 503 draining/dead,
413 prompt-too-long. The server drains gracefully: ``drain()`` stops
admissions, lets in-flight requests finish (bounded), flushes
exporters and the metrics log, then stops the listener.
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from tpunet.obs import tracing
from tpunet.serve import httpjson
from tpunet.serve.engine import Engine, PromptTooLongError
from tpunet.serve.scheduler import DrainingError, QueueFullError


def _token_text(tokens, vocab_size: int) -> Optional[str]:
    """Byte-level checkpoints (vocab 256) round-trip UTF-8; other
    vocabs have no text form."""
    if vocab_size != 256:
        return None
    return bytes(np.clip(np.asarray(tokens, np.int64), 0, 255)
                 .astype(np.uint8)).decode("utf-8", errors="replace")


class ServeServer:
    """Owns the engine, optional classifier batcher, obs sinks, and the
    HTTP listener. ``port=0`` binds an ephemeral port (tests)."""

    def __init__(self, engine: Engine, *, classify_batcher=None,
                 host: str = "127.0.0.1", port: int = 8000,
                 metrics_logger=None, exporters=(), run_id: str = "",
                 flight_recorder=None):
        self.engine = engine
        self.classify = classify_batcher
        self.registry = engine.registry
        if not self.registry.identity():
            # Replica identity on every obs_serve record: the fleet
            # aggregator routes replica streams by it (one replica =
            # one run_id). serve has no checkpoint-persisted id, so
            # the default is host+pid — stable for the server's life,
            # unique across replicas on one host.
            import os
            import socket
            self.registry.set_identity(
                run_id=run_id or f"serve-{socket.gethostname()}"
                                 f"-{os.getpid()}",
                process_index=0, host=socket.gethostname())
        self.vocab_size = int(engine.model.vocab_size)
        self._metrics_logger = metrics_logger
        self._exporters = list(exporters)
        # Flight recorder owned by this server's process (installed by
        # the serve entry when a metrics dir exists); drain marks the
        # clean shutdown so the watcher never fabricates a crash.
        self._flightrec = flight_recorder
        self._drained = False
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._serve_thread: Optional[threading.Thread] = None

    def start(self) -> "ServeServer":
        self.engine.start()
        # Host-thread registry (tpunet/obs/flightrec/, tpucheck R4):
        # inventory-only (stall budget 0 — serve_forever blocks in
        # accept(), so it cannot beat; liveness is the /healthz
        # contract, but the thread must still show up in crash
        # reports and thread_* gauges).
        from tpunet.obs import flightrec
        flightrec.register_thread("serve-http")
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="tpunet-serve-http")
        self._serve_thread.start()
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """SIGTERM path: stop admitting, finish in-flight, flush obs,
        stop listening. Idempotent."""
        if self._drained:
            return True
        self._drained = True
        from tpunet.obs import flightrec
        flightrec.record("serve", "frontend drain")
        ok = self.engine.drain(timeout)
        for exporter in self._exporters:
            try:
                exporter.close()
            except Exception:  # noqa: BLE001 — a dead endpoint must
                pass           # not block shutdown
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.classify is not None:
            self.classify.close()
        if self._flightrec is not None:
            flightrec.close(self._flightrec)
            self._flightrec = None
        return ok

    close = drain


def _make_handler(server: ServeServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Quiet by default: per-request stderr lines are noise at
        # serving rates; metrics carry the signal.

        def log_message(self, fmt, *args):  # noqa: D102
            pass

        # -- helpers ---------------------------------------------------

        def _json(self, code: int, obj: dict, headers=()) -> None:
            httpjson.write_json(self, code, obj, headers)

        def _retry_after(self):
            """503-draining responses carry Retry-After (seconds until
            this replica is expected back): the router backs the
            replica off for exactly that long instead of hammering a
            drain with requests it will reject."""
            return (("Retry-After",
                     str(max(1, int(server.engine.cfg.drain_timeout_s)))),)

        def _read_body(self) -> dict:
            return httpjson.read_json_body(self)

        # -- GET -------------------------------------------------------

        def do_GET(self):  # noqa: N802 (stdlib handler API)
            if self.path == "/healthz":
                engine = server.engine
                # Chaos injection (tpunet/serve/chaos.py): a standing
                # stall wedges the probe (the router's stall-evict
                # path); drop-probe answers 500 on the seeded draws.
                if engine.chaos is not None \
                        and engine.chaos.on_probe():
                    self._json(500, {"error": "chaos: probe dropped"})
                    return
                run_id = server.registry.identity().get("run_id", "")
                if engine.error is not None or not engine.healthy:
                    self._json(503, {
                        "status": "unhealthy", "run_id": run_id,
                        "error": engine.error or "engine thread dead"})
                elif engine.draining:
                    self._json(503, {"status": "draining",
                                     "run_id": run_id},
                               headers=self._retry_after())
                else:
                    self._json(200, {
                        "status": "ok", "run_id": run_id,
                        "active_slots": engine.active_slots(),
                        "queue_depth": engine.queue.depth(),
                        "slots": engine.slots})
                return
            if self.path == "/metrics":
                self._json(200, server.registry.snapshot())
                return
            self._json(404, {"error": "not found"})

        # -- POST ------------------------------------------------------

        def do_POST(self):  # noqa: N802
            try:
                body = self._read_body()
            except ValueError as e:
                self._json(400, {"error": str(e)})
                return
            if self.path == "/v1/generate":
                self._generate(body)
            elif self.path == "/v1/classify":
                self._classify(body)
            else:
                self._json(404, {"error": "not found"})

        def _parse_prompt(self, body: dict) -> np.ndarray:
            if "tokens" in body:
                toks = np.asarray(body["tokens"], np.int32).reshape(-1)
            elif "prompt" in body:
                if server.vocab_size != 256:
                    raise ValueError(
                        "text prompts need a byte-level (vocab 256) "
                        "checkpoint; send token ids as 'tokens'")
                toks = np.frombuffer(
                    str(body["prompt"]).encode("utf-8"),
                    np.uint8).astype(np.int32)
            else:
                raise ValueError("body needs 'prompt' or 'tokens'")
            if toks.size == 0:
                raise ValueError("prompt must be non-empty")
            if toks.min() < 0 or toks.max() >= server.vocab_size:
                raise ValueError(
                    f"token ids outside [0, {server.vocab_size})")
            return toks

        def _parse_resume(self, body: dict):
            """``resume_tokens`` (router mid-stream failover): token
            ids another replica already generated and streamed —
            validated like a prompt, but allowed to be absent."""
            if body.get("resume_tokens") is None:
                return None
            resume = np.asarray(body["resume_tokens"],
                                np.int32).reshape(-1)
            if resume.size and (resume.min() < 0
                                or resume.max() >= server.vocab_size):
                raise ValueError(
                    f"resume_tokens ids outside "
                    f"[0, {server.vocab_size})")
            return resume.tolist()

        def _deadline_s(self, body: dict) -> float:
            """Effective wall-clock deadline: the ``X-Deadline-Ms``
            header (the router propagates the client's original
            budget through every failover hop) and the body's
            ``deadline_s`` compose as the TIGHTER of the two."""
            body_s = float(body.get("deadline_s", 0.0))
            hdr = self.headers.get("X-Deadline-Ms")
            if hdr is None:
                return body_s
            hdr_s = float(hdr) / 1e3
            if hdr_s <= 0:
                raise ValueError(
                    f"X-Deadline-Ms must be positive, got {hdr!r}")
            return min(body_s, hdr_s) if body_s > 0 else hdr_s

        def _trace_context(self):
            """(trace_id, hop) for this request (tpunet/obs/
            tracing.py). A router upstream decides: its trace headers
            are adopted verbatim (``X-Trace-Sampled: 0`` would mean
            unsampled, but the router only stamps sampled hops).
            Standalone — no trace headers — a client-supplied
            ``X-Trace-Id`` is always sampled, and ``--trace-sample``
            head-samples the rest locally. ("", 0) = unsampled."""
            tid = self.headers.get(tracing.TRACE_HEADER)
            if tracing.valid_trace_id(tid):
                sampled = self.headers.get(tracing.SAMPLED_HEADER)
                if sampled is not None and sampled != "1":
                    return "", 0
                hop = self.headers.get(tracing.HOP_HEADER, "1")
                return tid, (int(hop) if hop.isdigit() else 1)
            rate = server.engine.cfg.trace_sample
            if rate > 0:
                tid = tracing.mint_trace_id()
                if tracing.should_sample(rate, tid):
                    return tid, 1
            return "", 0

        def _generate(self, body: dict) -> None:
            try:
                toks = self._parse_prompt(body)
                kw = {}
                if body.get("max_new_tokens") is not None:
                    # pass through verbatim: the engine defaults a
                    # MISSING budget and rejects an invalid one (0 ->
                    # ValueError -> 400), never silently substitutes.
                    kw["max_new_tokens"] = int(body["max_new_tokens"])
                resume = self._parse_resume(body)
                if resume is not None:
                    kw["resume_tokens"] = resume
                kw["trace_id"], kw["trace_hop"] = \
                    self._trace_context()
                req = server.engine.submit(
                    toks, **kw,
                    temperature=float(body.get("temperature", 0.0)),
                    top_k=int(body.get("top_k", 0)),
                    top_p=float(body.get("top_p", 0.0)),
                    seed=int(body.get("seed", 0)),
                    deadline_s=self._deadline_s(body),
                    stop_token=int(body["stop_token"])
                    if body.get("stop_token") is not None else None)
            except QueueFullError as e:
                self._json(429, {"error": "queue_full",
                                 "detail": str(e)})
                return
            except DrainingError as e:
                self._json(503, {"error": "draining", "detail": str(e)},
                           headers=self._retry_after())
                return
            except PromptTooLongError as e:
                self._json(413, {"error": "prompt_too_long",
                                 "detail": str(e)})
                return
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            if body.get("stream"):
                self._stream_response(req)
            else:
                self._sync_response(req)

        def _sync_response(self, req) -> None:
            try:
                tokens = req.result(timeout=600.0)
            except TimeoutError:
                req.cancel()
                self._json(504, {"error": "timeout"})
                return
            out = {
                "id": req.id,
                "tokens": tokens,
                "finish_reason": req.finish_reason,
                # The EFFECTIVE generation budget after the admission
                # clamp (operator cap / KV length) — a response shorter
                # than the ask is attributable to the clamp, not a bug.
                "max_new_tokens": req.max_new_tokens,
                "ttft_ms": round(1e3 * req.ttft_s, 3)
                if req.ttft_s is not None else None,
                "e2e_ms": round(1e3 * req.e2e_s, 3)
                if req.e2e_s is not None else None,
            }
            if req.requested_max_new_tokens != req.max_new_tokens:
                out["requested_max_new_tokens"] = \
                    req.requested_max_new_tokens
            text = _token_text(tokens, server.vocab_size)
            if text is not None:
                out["text"] = text
            if req.error:
                out["error"] = req.error
            self._json(200 if req.finish_reason != "error" else 500, out)

        def _stream_response(self, req) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(obj: dict) -> None:
                line = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(line):x}\r\n".encode()
                                 + line + b"\r\n")
                self.wfile.flush()

            chaos = server.engine.chaos
            # Every token event carries its index in the GENERATED
            # sequence ("i"): a resumed request starts at its resume
            # offset, so the router's failover relay can suppress a
            # duplicate at the kill seam by index instead of guessing.
            idx = req.resume_offset
            try:
                for kind, val in req.events(timeout=600.0):
                    if chaos is not None:
                        chaos.on_stream_line()   # slow-stream injection
                    if kind == "token":
                        ev = {"token": val, "i": idx}
                        idx += 1
                        text = _token_text([val], server.vocab_size)
                        if text is not None:
                            ev["text"] = text
                        chunk(ev)
                    else:
                        done = {"done": True, "finish_reason": val,
                                "n_tokens": len(req.tokens),
                                "max_new_tokens": req.max_new_tokens,
                                "ttft_ms": round(1e3 * req.ttft_s, 3)
                                if req.ttft_s is not None else None}
                        if req.requested_max_new_tokens \
                                != req.max_new_tokens:
                            done["requested_max_new_tokens"] = \
                                req.requested_max_new_tokens
                        chunk(done)
                self.wfile.write(b"0\r\n\r\n")
            except TimeoutError:
                # Wedged engine: free the slot and tell the (still
                # connected) client before terminating the stream.
                req.cancel()
                try:
                    chunk({"done": True, "finish_reason": "error",
                           "error": "timed out waiting for the engine"})
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass
            except (BrokenPipeError, ConnectionResetError, OSError):
                # Client went away mid-stream: free the slot. The
                # disconnect is a lifecycle event too — on the unified
                # timeline a decode phase ending in "cancelled" with a
                # client_gone mark next to it reads as the client's
                # fault, not the engine's.
                from tpunet.obs import flightrec
                flightrec.record("req", f"client_gone {req.id}")
                req.cancel()

        def _classify(self, body: dict) -> None:
            if server.classify is None:
                self._json(503, {"error": "no classifier configured"})
                return
            try:
                if "image_b64" in body:
                    shape = tuple(body.get("shape") or ())
                    if len(shape) != 3 or shape[2] != 3:
                        raise ValueError(
                            "'image_b64' needs 'shape': [H, W, 3]")
                    raw = base64.b64decode(body["image_b64"])
                    img = np.frombuffer(raw, np.uint8)
                    if img.size != shape[0] * shape[1] * 3:
                        raise ValueError(
                            f"image_b64 has {img.size} bytes, shape "
                            f"{shape} needs {shape[0]*shape[1]*3}")
                    img = img.reshape(shape)
                elif "image" in body:
                    img = np.asarray(body["image"])
                    if img.ndim != 3 or img.shape[-1] != 3:
                        raise ValueError("'image' must be HWC with 3 "
                                         "channels")
                    img = np.clip(img, 0, 255).astype(np.uint8)
                else:
                    raise ValueError("body needs 'image' or 'image_b64'")
                probs = server.classify.submit(img)
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            except (RuntimeError, TimeoutError) as e:
                self._json(500, {"error": str(e)})
                return
            topk = int(body.get("topk", 3))
            names = server.classify.predictor.class_names
            order = np.argsort(probs)[::-1][:max(1, topk)]
            self._json(200, {
                "topk": [{"label": names[i], "prob": float(probs[i])}
                         for i in order],
                "probs": {names[i]: float(probs[i])
                          for i in range(len(names))}})

    return Handler
