"""Shared JSON helpers for the stdlib HTTP handlers.

The serve frontend and the router frontend speak the same wire shapes
(JSON bodies in, JSON + optional extra headers out); keeping the two
implementations in one place means a fix to either — charset, error
payload shape, a Content-Length edge case — cannot silently miss the
other surface.
"""

from __future__ import annotations

import json
from typing import Iterable, Tuple

Headers = Iterable[Tuple[str, str]]


def write_json(handler, code: int, obj: dict,
               headers: Headers = ()) -> None:
    """Send one JSON response (Content-Length framed) with optional
    extra headers (e.g. Retry-After on 503s)."""
    body = json.dumps(obj).encode()
    handler.send_response(code)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    for name, value in headers:
        handler.send_header(name, value)
    handler.end_headers()
    handler.wfile.write(body)


def read_json_body(handler) -> dict:
    """Read and parse the request body; raises ValueError on invalid
    JSON or a non-object top level (callers map it to 400)."""
    n = int(handler.headers.get("Content-Length") or 0)
    if n <= 0:
        return {}
    raw = handler.rfile.read(n)
    try:
        obj = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"invalid JSON body: {e}")
    if not isinstance(obj, dict):
        raise ValueError("body must be a JSON object")
    return obj
