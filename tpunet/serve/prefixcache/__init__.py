"""Fleet-wide prefix KV cache (docs/serving.md "Prefix KV cache").

Prefill pages as first-class, immutable, content-addressed objects:

- :mod:`keys` — the token-prefix digest convention shared with the
  router's affinity hashing and the spill store's file names;
- :mod:`cache` — the per-replica refcounted trie of pages living
  inside the engine's paged KV pool (pin on admission, unpin on
  release, LRU-evict under pool pressure);
- :mod:`store` — shared-filesystem spill/warm-start via the fsatomic
  first-writer-wins commit the AOT store proved.

The engine (tpunet/serve/engine.py) is the only writer; the router
only hashes digests.
"""

from tpunet.serve.prefixcache.cache import PrefixCache, PrefixNode
from tpunet.serve.prefixcache.keys import (ROOT, chain_digests,
                                           token_prefix_digest)
from tpunet.serve.prefixcache.store import PrefixStore, build_prefix_store

__all__ = [
    "PrefixCache",
    "PrefixNode",
    "PrefixStore",
    "ROOT",
    "build_prefix_store",
    "chain_digests",
    "token_prefix_digest",
]
