"""Per-replica prefix KV cache: refcounted, content-addressed pages
inside the engine's shared page pool.

The cache does NOT own device memory — every cached page lives in the
same per-layer flat pool the engine's slots allocate from (page 0
stays the reserved garbage page). What the cache owns is the HOST
bookkeeping that lets finished prefills outlive their slot:

- a trie of :class:`PrefixNode`, one node per cached full page,
  keyed by the digest of the token prefix THROUGH that page
  (``keys.token_prefix_digest(tokens, (depth+1)*page_tokens)``) — so
  two prompts sharing the first k pages share the first k nodes;
- a refcount per node (slots currently mapping the page into their
  page table) — pinned pages are immutable and never freed;
- an LRU over EVICTABLE nodes: ``refs == 0`` and no children.
  Leaf-first eviction keeps every cached chain prefix-closed, which
  is what makes lookup's "walk down while present" correct.

Threading: all mutation happens on the engine thread (the same
discipline as the page allocator); no locks here.

Safety argument for sharing (docs/serving.md "Prefix KV cache"): the
paged attend write path scatters at ``positions >= start`` only, and
a slot that pinned k pages prefills with ``positions = k*page_tokens``
— pinned pages are never written by construction, so a cached page's
K/V rows are bitwise-frozen from insert to eviction. The recycling
stress test extends the zero-stale-bleed proof to this regime.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from tpunet.serve.prefixcache import keys


class PrefixNode:
    """One cached full page of prefill K/V.

    ``depth`` d covers tokens ``[d*page_tokens, (d+1)*page_tokens)``;
    ``digest`` is the flat digest of the token prefix through the end
    of this page; ``parent`` is the depth d-1 node (None at depth 0).
    ``page`` is the pool page index holding the rows. ``refs`` counts
    slots whose page table currently maps this page. ``tick`` is the
    cache's logical clock at last touch (LRU order).
    """

    __slots__ = ("digest", "parent", "children", "page", "refs",
                 "tick", "depth")

    def __init__(self, digest: str, parent: Optional["PrefixNode"],
                 depth: int, page: int):
        self.digest = digest
        self.parent = parent
        self.children: set = set()
        self.page = page
        self.refs = 0
        self.tick = 0
        self.depth = depth


class PrefixCache:
    """Bounded trie of refcounted prefix pages (host side only).

    ``capacity`` bounds how many pool pages the cache may hold at
    refs == 0 + refs > 0 combined — the engine sizes it below the
    pool so paying slots always have headroom, and calls
    :meth:`evict_one` under pool pressure before failing an
    allocation.
    """

    def __init__(self, page_tokens: int, capacity: int, *,
                 registry=None):
        self.page_tokens = int(page_tokens)
        self.capacity = int(capacity)
        self._nodes: Dict[str, PrefixNode] = {}
        self._tick = 0
        self._reg = registry
        if registry is not None:
            self._c_lookups = registry.counter("serve_prefix_lookups_total")
            self._c_hits = registry.counter("serve_prefix_hits_total")
            self._c_hit_tokens = registry.counter(
                "serve_prefix_hit_tokens_total")
            self._c_inserts = registry.counter("serve_prefix_inserts_total")
            self._c_evictions = registry.counter(
                "serve_prefix_evictions_total")
            self._g_pages = registry.gauge("serve_prefix_pages_cached")
        else:
            self._c_lookups = self._c_hits = self._c_hit_tokens = None
            self._c_inserts = self._c_evictions = self._g_pages = None

    # -- introspection ---------------------------------------------------

    def __len__(self) -> int:
        return len(self._nodes)

    @property
    def pages_cached(self) -> int:
        return len(self._nodes)

    def pinned_pages(self) -> int:
        return sum(1 for n in self._nodes.values() if n.refs > 0)

    def evictable_pages(self) -> int:
        return sum(1 for n in self._nodes.values()
                   if n.refs == 0 and not n.children)

    def get(self, digest: str) -> Optional[PrefixNode]:
        return self._nodes.get(digest)

    # -- lookup / pin ----------------------------------------------------

    def lookup(self, tokens: Sequence[int],
               max_pages: int) -> List[PrefixNode]:
        """The longest cached chain covering the first full pages of
        ``tokens``, capped at ``max_pages`` — counted as one lookup
        (and one hit when non-empty). Does NOT pin; the engine pins
        only once the slot's remaining allocation succeeded."""
        chain: List[PrefixNode] = []
        pt = self.page_tokens
        for d in range(max_pages):
            node = self._nodes.get(
                keys.token_prefix_digest(tokens, (d + 1) * pt))
            if node is None:
                break
            chain.append(node)
        if self._c_lookups is not None:
            self._c_lookups.inc()
            if chain:
                self._c_hits.inc()
                self._c_hit_tokens.inc(len(chain) * pt)
        return chain

    def pin(self, nodes: Sequence[PrefixNode]) -> None:
        """refcount++ each node (slot admission mapped its page)."""
        self._tick += 1
        for n in nodes:
            n.refs += 1
            n.tick = self._tick

    def unpin(self, nodes: Sequence[PrefixNode]) -> None:
        """refcount-- each node (slot released its page table). The
        page stays cached — eviction, not release, returns it to the
        free list."""
        self._tick += 1
        for n in nodes:
            n.refs -= 1
            assert n.refs >= 0, "prefix page unpinned below zero"
            n.tick = self._tick

    # -- insert / evict --------------------------------------------------

    def insert(self, digest: str, parent: Optional[PrefixNode],
               depth: int, page: int) -> PrefixNode:
        """Adopt ``page`` (already holding the rows for this chain
        position) as a cached node. The caller has already checked
        ``get(digest) is None`` — concurrent-duplicate dedup is the
        engine's job because the duplicate page must go back to the
        pool. The node is returned UNPINNED; the caller pins it if a
        slot still maps it."""
        assert digest not in self._nodes
        node = PrefixNode(digest, parent, depth, page)
        if parent is not None:
            parent.children.add(node)
        self._tick += 1
        node.tick = self._tick
        self._nodes[digest] = node
        if self._c_inserts is not None:
            self._c_inserts.inc()
            self._g_pages.set(len(self._nodes))
        return node

    def evict_one(self) -> Optional[int]:
        """Drop the least-recently-touched evictable node (refs == 0,
        no children) and return its pool page for the free list; None
        when nothing is evictable (every cached page is pinned by a
        live slot or interior to a pinned chain)."""
        victim: Optional[PrefixNode] = None
        for n in self._nodes.values():
            if n.refs == 0 and not n.children:
                if victim is None or n.tick < victim.tick:
                    victim = n
        if victim is None:
            return None
        del self._nodes[victim.digest]
        if victim.parent is not None:
            victim.parent.children.discard(victim)
        if self._c_evictions is not None:
            self._c_evictions.inc()
            self._g_pages.set(len(self._nodes))
        return victim.page
