"""Content addresses for prefix KV pages.

ONE digest convention shared by the three parties that must agree on
what "the same prefix" means (docs/serving.md "Prefix KV cache"):

- the router's rendezvous affinity key (``tpunet.router.balance``
  hashes ``token_prefix_digest`` so shared-prefix traffic lands on
  the replica already holding those pages),
- the per-replica in-pool cache (``PrefixCache`` keys each cached
  page by the digest of the token prefix THROUGH that page),
- the shared-filesystem spill store (``PrefixStore`` names entries
  ``<store_digest>-<chain_digest>`` so a respawned replica loads
  exactly the prefixes the fleet's routers are steering at it).

The digest is FLAT, not incremental: sha256 over the little-endian
int32 bytes of ``tokens[:n]``. A chained/rolling form would be
cheaper per page but couples every consumer to the chaining order;
prompts are short enough that re-hashing the prefix per page boundary
is noise next to the prefill it replaces.

Config partitioning (model fingerprint, kv levers, jax version,
device kind) is deliberately NOT folded in here — the in-pool cache
lives inside one engine so every entry trivially shares its config,
and the spill store scopes files by its own ``store_digest`` prefix.
Keeping token digests config-free is what lets the router (which
knows nothing about model configs) hash the same bytes.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

#: Parent key of a depth-0 cache node (no token prefix above it).
ROOT = "root"


def token_prefix_digest(tokens: Sequence[int], n: int) -> str:
    """Stable 16-hex digest of ``tokens[:n]`` (little-endian int32
    bytes — the dtype prompts are staged in on the host)."""
    h = hashlib.sha256()
    for t in tokens[:n]:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return h.hexdigest()[:16]


def chain_digests(tokens: Sequence[int], page_tokens: int,
                  pages: int) -> list:
    """Digest of the token prefix through each of the first ``pages``
    full pages: element ``d`` keys the page covering tokens
    ``[d*page_tokens, (d+1)*page_tokens)``."""
    return [token_prefix_digest(tokens, (d + 1) * page_tokens)
            for d in range(pages)]
