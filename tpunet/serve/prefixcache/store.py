"""Shared-filesystem spill/warm-start for prefix KV pages.

One store = one directory of ``<store_digest>-<chain_digest>.pfx``
files, each a pickled dict: the chain digest, the parent's digest
(``keys.ROOT`` at depth 0), the depth, and the page's K/V rows as
host numpy arrays keyed by flattened cache-tree path. The chain
digest is the same token-prefix digest the in-pool cache and the
router hash (``keys``); ``store_digest`` scopes every entry by what
makes pages interchangeable across replicas — model config, kv page
geometry + dtype, jax version, device kind — so a lever change is a
clean MISS, never stale K/V.

Commit discipline is ``tpunet.utils.fsatomic``: content-digest tmp +
rename under a flock-guarded first-writer-wins check, exactly the
shared-filesystem story the AOT program store proved. N replicas
spilling the same fleet-common system prefix write it once.

``save`` is write-through at insert time and best-effort (a read-only
disk degrades to a per-replica cache, never a crash); ``load_all``
yields entries sorted by depth so a warming replica can insert each
page only after its parent landed (capacity may truncate a chain —
depth order guarantees the kept prefix is still prefix-closed).
"""

from __future__ import annotations

import glob
import os
import pickle
from typing import Iterator, Optional

from tpunet.utils import fsatomic

SUFFIX = ".pfx"


class PrefixStore:
    def __init__(self, directory: str, store_digest: str):
        self.directory = directory
        self.store_digest = store_digest

    def _path(self, chain_digest: str) -> str:
        return os.path.join(
            self.directory,
            f"{self.store_digest}-{chain_digest}{SUFFIX}")

    def exists(self, chain_digest: str) -> bool:
        return os.path.exists(self._path(chain_digest))

    def save(self, chain_digest: str, parent_digest: str, depth: int,
             rows: dict) -> bool:
        """Publish one page's rows (host numpy arrays keyed by
        flattened tree path). First writer wins; an existing entry is
        never rewritten. False on any OS failure."""
        payload = pickle.dumps({
            "digest": chain_digest,
            "parent": parent_digest,
            "depth": int(depth),
            "rows": rows,
        })
        try:
            return fsatomic.publish_bytes(self._path(chain_digest),
                                          payload)
        except OSError:
            return False

    def load_all(self, limit: Optional[int] = None) -> Iterator[dict]:
        """Entries for THIS store digest, shallowest first (parents
        before children), corrupt/foreign files skipped. ``limit``
        bounds how many are even read — warm-start is capacity-bound
        anyway."""
        pattern = os.path.join(self.directory,
                               self.store_digest + "-*" + SUFFIX)
        entries = []
        for path in sorted(glob.glob(pattern)):
            try:
                with open(path, "rb") as f:
                    entry = pickle.load(f)
                entries.append(entry)
            except Exception:  # noqa: BLE001 — torn/foreign file:
                continue       # warm-start is best-effort.
        entries.sort(key=lambda e: int(e.get("depth", 0)))
        if limit is not None:
            entries = entries[:limit]
        return iter(entries)


def build_prefix_store(directory: str, model_cfg,
                       serve_cfg) -> PrefixStore:
    """A store scoped by everything that makes a spilled page safe to
    map into THIS engine's pool: the full model config, the kv page
    geometry and dtype, and the runtime (jax version + device kind —
    quantization rounding may differ across backends)."""
    import dataclasses

    import jax

    from tpunet.utils.cache import AotProgramStore

    digest = AotProgramStore.digest({
        "model": dataclasses.asdict(model_cfg),
        "kv_page_tokens": serve_cfg.kv_page_tokens,
        "kv_dtype": serve_cfg.kv_dtype,
        "jax": jax.__version__,
        "device_kind": jax.devices()[0].device_kind,
    })
    return PrefixStore(directory, digest)
