"""Device-side batched sampling for the continuous-batching engine.

The engine's original token path sampled on the HOST: one full-vocab
logits row ferried off-device per slot per step, then a Python loop of
numpy top-k/top-p/categorical per request. At serving batch widths
that loop (and the [slots, V] transfer feeding it) caps tokens/s long
before the device does. ``batched_sample`` moves the whole choice
on-device as ONE ``[slots]``-wide jitted computation fused onto the
decode step — the host loop leaves the token path and only the sampled
int32 tokens cross the boundary.

Semantics mirror ``models.lm.filter_logits`` exactly (sequential
HF-warper order: top-k truncation first, then the nucleus over the
RENORMALIZED post-top-k distribution), generalized to PER-ROW
parameters: every slot carries its own temperature/top_k/top_p/seed,
because co-resident requests disagree about all four. Greedy rows
(temperature <= 0) are exact ``argmax`` over the raw float32 logits —
bit-identical to the host sampler's ``np.argmax`` on the same array,
which is what keeps greedy serve output token-identical to solo
``models.lm.generate``.

Randomness is counter-based: row b's key is
``fold_in(fold_in(PRNGKey(seed_b), SALT), step_b)`` where ``step_b``
is how many tokens the request has generated so far. Keys never live
between steps (nothing to checkpoint, nothing to desync), the stream
is deterministic per (seed, step) — a preempted-and-resumed request
continues its exact sample sequence — and rows are independent across
slots by construction.

The host sampler (``engine.sample_token``) stays as the parity
reference and the ``--no-device-sampling`` fallback.
"""

from __future__ import annotations

# Salt folded into every per-request key so the serve sample stream
# can never collide with a training PRNG stream built from the same
# user seed.
_SAMPLE_SALT = 0x5E12


def batched_sample(logits, temperature, top_k, top_p, seeds, steps):
    """One sampled token per row from ``logits`` [B, V] float32.

    ``temperature``/``top_p`` are float32 [B], ``top_k``/``seeds``/
    ``steps`` int32 [B]. Rows with ``temperature <= 0`` are greedy
    argmax of the RAW logits; other rows follow filter_logits
    semantics with a per-(seed, step) categorical draw. Returns int32
    [B].
    """
    import jax
    import jax.numpy as jnp

    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def stochastic():
        # Scaled logits for the sampling branch (safe divisor for
        # greedy rows — their result is discarded by the where()).
        safe_t = jnp.where(temperature > 0, temperature, 1.0)
        lg = logits / safe_t[:, None]

        srt = jnp.sort(lg, axis=-1)[:, ::-1]                  # [B, V] desc
        # -- per-row top-k (filter_logits: keep lg >= k-th largest) ---
        apply_k = (top_k > 0) & (top_k < v)
        kth = jnp.take_along_axis(
            srt, jnp.clip(top_k - 1, 0, v - 1)[:, None], axis=1)
        lg = jnp.where(apply_k[:, None] & (lg < kth), -jnp.inf, lg)
        srt = jnp.where(apply_k[:, None]
                        & (jnp.arange(v)[None, :] >= top_k[:, None]),
                        -jnp.inf, srt)
        # -- per-row nucleus over the renormalized post-top-k dist ----
        apply_p = (top_p > 0.0) & (top_p < 1.0)
        probs = jax.nn.softmax(srt, axis=-1)
        keep = jnp.cumsum(probs, axis=-1) - probs < top_p[:, None]
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)                       # [B, 1]
        lg = jnp.where(apply_p[:, None] & (lg < cutoff), -jnp.inf, lg)

        def draw(key_seed, key_step, row):
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(key_seed),
                                   _SAMPLE_SALT), key_step)
            return jax.random.categorical(key, row)

        sampled = jax.vmap(draw)(seeds, steps, lg).astype(jnp.int32)
        return jnp.where(temperature > 0, sampled, greedy)

    # Greedy batches are the common serving case: skip the whole
    # sort/softmax/cumsum/per-row-PRNG pipeline at runtime unless at
    # least one resident row actually samples.
    return jax.lax.cond(jnp.any(temperature > 0), stochastic,
                        lambda: greedy)


def batched_sample_positions(logits, temperature, top_k, top_p, seeds,
                             steps0):
    """Per-position sampling for the speculative verify step: one
    token per (row, position) from ``logits`` [B, T, V] float32.

    Position ``j`` of row ``b`` draws with step ``steps0[b] + j`` —
    exactly the key the sequential decode loop would have used when
    it reached that position, which is what makes spec-on sampled
    output bitwise-identical to spec-off per (seed, step) and keeps
    failover resume deterministic. ``T`` is static (K+1), so the
    per-position loop unrolls at trace time into T reuses of the
    [B]-wide ``batched_sample``. Returns int32 [B, T].
    """
    import jax.numpy as jnp

    t = logits.shape[1]
    cols = [batched_sample(logits[:, j], temperature, top_k, top_p,
                           seeds, steps0 + j) for j in range(t)]
    return jnp.stack(cols, axis=1)
