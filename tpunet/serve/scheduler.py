"""Admission queue and request lifecycle for the serving engine.

The queue is the backpressure point: admission is FIFO and BOUNDED —
when ``queue_max`` requests are already waiting, ``submit`` raises
``QueueFullError`` immediately (the frontend maps it to 429) instead of
letting queue latency grow without bound. Everything past admission is
cooperative: a request carries a cancel flag and an absolute deadline,
both checked by the engine at iteration boundaries (a cancelled or
expired request frees its KV slot within one decode iteration, it is
never interrupted mid-step).

``GenerateRequest`` doubles as the response channel: the engine pushes
token events into a per-request queue (the streaming frontend drains it
as ndjson), and ``result()`` blocks until the request finishes for the
non-streaming path.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
from typing import List, Optional


class QueueFullError(Exception):
    """Admission bound hit: reject-with-429, never queue-and-degrade."""


class DrainingError(Exception):
    """Server is draining: no new admissions."""


_ids = itertools.count(1)

# Sentinel finish reasons (mirrored into the HTTP response and the
# serve_finished_<reason> counters).
FINISH_LENGTH = "length"          # max_new_tokens generated
FINISH_STOP = "stop"              # stop_token sampled
FINISH_DEADLINE = "deadline"      # wall-clock deadline hit
FINISH_CANCELLED = "cancelled"    # client cancelled / disconnected
FINISH_ERROR = "error"            # engine failure
FINISH_DRAIN = "drain"            # cancelled by shutdown drain timeout


class GenerateRequest:
    """One in-flight generation: prompt tokens in, token events out.

    ``deadline_s`` is wall-clock seconds from submission (0 = none);
    sampling parameters follow models.lm.generate semantics
    (temperature 0 = greedy; top_k/top_p filter sampling only).
    """

    def __init__(self, prompt, *, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 deadline_s: float = 0.0,
                 stop_token: Optional[int] = None,
                 resume_tokens=None, trace_id: str = "",
                 trace_hop: int = 0):
        import numpy as np
        self.id = next(_ids)
        # Trace context (tpunet/obs/tracing.py): ``self.id`` is
        # per-PROCESS; the (trace_id, trace_hop) pair the router
        # stamped on the hop's headers is what names this span across
        # the fleet. Empty trace_id = unsampled; every trace call
        # site short-circuits on it.
        self.trace_id = str(trace_id)
        self.trace_hop = int(trace_hop)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("prompt must contain at least one token")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        # What the CLIENT asked for, before any admission clamp (cap /
        # KV length). ``max_new_tokens`` becomes the EFFECTIVE budget;
        # the frontend reports both so a silently-shortened response
        # is attributable to the clamp, not a bug.
        self.requested_max_new_tokens = self.max_new_tokens
        # Times this request was preempted out of its slot (paged-KV
        # pool exhaustion) and re-queued for resume-prefill.
        self.preemptions = 0
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        # Both sampler backends need this range: numpy's Generator
        # rejects negatives (an engine-thread raise marks the whole
        # engine dead) and the device path folds the seed into an
        # int32 lane (values past bit 31 would silently collide).
        if not 0 <= self.seed < 2 ** 31:
            raise ValueError(
                f"seed must be in [0, 2**31), got {seed}")
        self.stop_token = stop_token
        self.submitted_t = time.perf_counter()
        self.deadline_t = (self.submitted_t + deadline_s
                           if deadline_s > 0 else None)
        self.first_token_t: Optional[float] = None
        self.done_t: Optional[float] = None
        # Phase stamps for the TTFT decomposition (queue vs prefill
        # vs first-decode) the ``obs_trace`` record and bench_serve
        # report. Set by the engine at admission / prefill; cheap
        # enough to stamp unconditionally (sampled or not).
        self.prefill_start_t: Optional[float] = None
        self.prefill_done_t: Optional[float] = None
        self.prefill_bucket: Optional[int] = None
        # Wall-clock spent preempted out of a slot (paged-KV pool
        # pressure): accumulated preempt -> resume-prefill.
        self.preempt_wall_s = 0.0
        self._preempt_t: Optional[float] = None
        # Cross-replica resume (router mid-stream failover,
        # docs/serving.md "Mid-stream failover & serve-tier chaos"):
        # tokens another replica already generated AND streamed to the
        # client. They seed ``self.tokens`` — the engine re-prefills
        # prompt+generated and the per-(seed, step) sampling keys
        # continue the exact stream — but are NEVER re-emitted as
        # events: the client already has them. ``resume_offset`` is
        # where this replica's token indices start.
        self.tokens: List[int] = ([int(t) for t in resume_tokens]
                                  if resume_tokens is not None else [])
        self.resume_offset = len(self.tokens)
        if self.resume_offset and self.max_new_tokens \
                < self.resume_offset:
            raise ValueError(
                f"resume_tokens carries {self.resume_offset} tokens "
                f"but max_new_tokens is {max_new_tokens}")
        self.finish_reason: Optional[str] = None
        self.error: Optional[str] = None
        self._events: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._cancelled = threading.Event()
        self._rng = None  # lazily-built numpy Generator (sampled reqs)

    # -- engine side ----------------------------------------------------

    def rng(self):
        if self._rng is None:
            import numpy as np
            self._rng = np.random.default_rng(self.seed)
        return self._rng

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline_t is not None
                and (now or time.perf_counter()) >= self.deadline_t)

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def push_token(self, token: int) -> None:
        # The ONLY producer of ('token', t) events — everything
        # downstream (streaming frontend, router relay, failover
        # journal) sees exactly this sequence. Speculative decoding
        # preserves that contract structurally: the engine pushes only
        # VERIFIED tokens (draft proposals never reach a request), so
        # a journal replayed after a mid-verify replica death resumes
        # from a prefix of the canonical stream, never from drafts.
        now = time.perf_counter()
        if self.first_token_t is None:
            self.first_token_t = now
        self.tokens.append(int(token))
        self._events.put(("token", int(token)))

    def finish(self, reason: str, error: Optional[str] = None) -> None:
        if self._done.is_set():
            return
        self.finish_reason = reason
        self.error = error
        self.done_t = time.perf_counter()
        self._events.put(("done", reason))
        self._done.set()

    # -- client side ----------------------------------------------------

    def cancel(self) -> None:
        """Cooperative: the engine frees the slot at its next iteration
        boundary (and ``finish``es the request there)."""
        self._cancelled.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def events(self, timeout: Optional[float] = None):
        """Yield ('token', id) events as they arrive, ending with
        ('done', reason). ``timeout`` bounds the wait for EACH event;
        expiry raises TimeoutError (a wedged engine must not hang a
        streaming client forever — callers cancel on it)."""
        while True:
            try:
                kind, val = self._events.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"request {self.id}: no event for {timeout}s")
            yield kind, val
            if kind == "done":
                return

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until finished; returns the generated tokens."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.id} not done "
                               f"after {timeout}s")
        return list(self.tokens)

    # -- metrics --------------------------------------------------------

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t

    @property
    def e2e_s(self) -> Optional[float]:
        if self.done_t is None:
            return None
        return self.done_t - self.submitted_t

    # TTFT decomposition: queue_s + prefill_s + first_decode_s ~=
    # ttft_s (the residual is host scheduling slack). Each is None
    # until its closing stamp exists.

    @property
    def queue_s(self) -> Optional[float]:
        if self.prefill_start_t is None:
            return None
        return self.prefill_start_t - self.submitted_t

    @property
    def prefill_s(self) -> Optional[float]:
        if self.prefill_start_t is None \
                or self.prefill_done_t is None:
            return None
        return self.prefill_done_t - self.prefill_start_t

    @property
    def first_decode_s(self) -> Optional[float]:
        if self.prefill_done_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.prefill_done_t


class RequestQueue:
    """Bounded FIFO admission queue shared by frontend and engine.

    ``on_finish(req, reason)`` is invoked for every request the QUEUE
    finishes (cancelled/expired while waiting, failed by ``fail_all``)
    so the engine's finish accounting covers requests that never
    reached a slot — without it, dashboards show phantom forever-in-
    flight requests."""

    def __init__(self, queue_max: int, on_finish=None):
        if queue_max < 1:
            raise ValueError(f"queue_max must be >= 1, got {queue_max}")
        self.queue_max = queue_max
        self._on_finish = on_finish
        self._lock = threading.Lock()
        self._waiting: "collections.deque[GenerateRequest]" = \
            collections.deque()
        self._closed = False

    def _finish(self, req: GenerateRequest, reason: str,
                error: Optional[str] = None) -> None:
        req.finish(reason, error=error)
        if self._on_finish is not None:
            self._on_finish(req, reason)

    def submit(self, req: GenerateRequest) -> None:
        with self._lock:
            if self._closed:
                raise DrainingError("server is draining")
            if len(self._waiting) >= self.queue_max:
                raise QueueFullError(
                    f"admission queue full ({self.queue_max} waiting)")
            self._waiting.append(req)

    def requeue_front(self, reqs) -> None:
        """Put already-admitted requests BACK at the head of the queue
        (paged-KV preemption, or an admission wave that ran out of
        pages mid-batch). Deliberately ignores ``closed`` and the
        bound: these requests were already admitted once — bouncing
        them now would turn a transient pool-pressure event into a
        client-visible failure."""
        with self._lock:
            for req in reversed(list(reqs)):
                self._waiting.appendleft(req)

    def pop_ready(self, n: int) -> List[GenerateRequest]:
        """Pop up to ``n`` admissible requests FIFO. Requests that were
        cancelled or expired while waiting are finished here (their
        deadline applies to queue time too) and don't consume a slot."""
        out: List[GenerateRequest] = []
        now = time.perf_counter()
        dropped = []
        with self._lock:
            while self._waiting and len(out) < n:
                req = self._waiting.popleft()
                if req.cancelled:
                    dropped.append((req, FINISH_CANCELLED))
                elif req.expired(now):
                    dropped.append((req, FINISH_DEADLINE))
                else:
                    out.append(req)
        for req, reason in dropped:      # outside the lock
            self._finish(req, reason)
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._waiting)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> List[GenerateRequest]:
        """Stop admitting (drain). Returns the requests still waiting —
        the engine keeps consuming them; the drain timeout decides
        whether they run or get cancelled."""
        with self._lock:
            self._closed = True
            return list(self._waiting)

    def fail_all(self, error: str) -> None:
        """Engine died: every waiting request fails fast."""
        with self._lock:
            waiting, self._waiting = list(self._waiting), \
                collections.deque()
            self._closed = True
        for req in waiting:
            self._finish(req, FINISH_ERROR, error=error)
