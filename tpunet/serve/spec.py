"""Speculative decoding support: drafter construction + acceptance.

Draft-then-verify decoding (docs/serving.md "Speculative decoding"):
a narrow drafter proposes ``K`` tokens per active slot against its own
paged KV pool, the serving model scores all ``K+1`` positions in ONE
batched forward over the main pool, and the engine keeps the longest
verified prefix. This module owns everything that is NOT engine
plumbing:

- ``drafter_model_config``: the width_mult lever applied to the
  serving ``ModelConfig`` (vit_hidden scaled, kept divisible by
  vit_heads so head_dim stays integral).
- ``accept_drafts``: the pure acceptance rule. Verify consumes
  ``[next_token, d_1..d_K]`` and produces choices ``c_0..c_K`` where
  ``c_j`` is the model's (sampled or greedy) token AFTER position
  ``pos+j``. The accepted count ``a`` is the longest prefix with
  ``d_j == c_{j-1}``; the engine emits ``c_0..c_a`` — every emitted
  token comes from the VERIFY distribution, so the output stream is
  bitwise-identical to non-speculative decoding at ANY acceptance
  rate (greedy and per-(seed, step) sampled alike).
- ``save_drafter_params`` / ``load_drafter_params``: flat-npz
  round-trip for drafter checkpoints (``--spec-draft-checkpoint``).
- ``fit_drafter``: deterministic distillation of a drafter onto the
  serving model's own greedy trajectories (hard-target cross-entropy,
  hand-rolled Adam — no optimizer deps on the serve path). This is
  the production fitting flow in miniature: you fit the drafter to
  the traffic you serve; ``bench_serve.py --spec`` fits against the
  bench workload's prompts the same way an operator distills against
  logged traffic.

Everything here is deterministic by construction — same inputs, same
drafter, same acceptance — because failover resume and bitwise replay
(tests/test_failover.py) depend on it.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tpunet.config import ModelConfig

__all__ = [
    "drafter_model_config",
    "accept_drafts",
    "save_drafter_params",
    "load_drafter_params",
    "fit_drafter",
]


def drafter_model_config(cfg: ModelConfig,
                         width_mult: float) -> ModelConfig:
    """The drafter's ModelConfig: ``vit_hidden`` scaled by
    ``width_mult`` and rounded DOWN to the nearest multiple of
    ``vit_heads`` (floor one full head) so attention head_dim stays
    integral. Depth, vocab, and max_seq_len are preserved — the
    drafter must cover the same positions the serving model does."""
    if width_mult <= 0:
        raise ValueError(f"spec_draft_width_mult must be > 0, "
                         f"got {width_mult}")
    heads = cfg.vit_heads
    hidden = int(cfg.vit_hidden * width_mult) // heads * heads
    hidden = max(heads, hidden)
    return dataclasses.replace(cfg, vit_hidden=hidden)


def accept_drafts(drafts: np.ndarray, choices: np.ndarray) -> np.ndarray:
    """Accepted-token counts per row.

    ``drafts``: ``[B, K]`` drafter proposals ``d_1..d_K``.
    ``choices``: ``[B, K+1]`` verify outputs ``c_0..c_K`` (the model's
    token after each of positions ``pos..pos+K``).

    Returns ``a`` ``[B]`` with ``0 <= a[i] <= K``: the longest prefix
    where ``d_j == c_{j-1}``. The engine then emits ``c_0..c_a`` —
    ``a+1`` tokens, all from the verify pass. ``c_a`` doubles as the
    next cycle's input token (the "bonus" token on full acceptance).
    """
    drafts = np.asarray(drafts)
    choices = np.asarray(choices)
    if drafts.ndim != 2 or choices.ndim != 2 \
            or choices.shape != (drafts.shape[0], drafts.shape[1] + 1):
        raise ValueError(
            f"shape mismatch: drafts {drafts.shape} vs choices "
            f"{choices.shape} (want [B, K] and [B, K+1])")
    match = drafts == choices[:, :-1]
    # First mismatch position == accepted count; all-match rows accept
    # the full K (argmin on an all-True row returns 0, so patch them).
    a = np.argmin(match, axis=1)
    a[match.all(axis=1)] = drafts.shape[1]
    return a.astype(np.int64)


def _flatten(params, prefix=""):
    out = {}
    for key in sorted(params):
        val = params[key]
        path = f"{prefix}/{key}" if prefix else str(key)
        if isinstance(val, dict):
            out.update(_flatten(val, path))
        else:
            out[path] = np.asarray(val)
    return out


def save_drafter_params(path: str, params) -> None:
    """Write a drafter param tree as a flat ``.npz`` (keys are
    ``/``-joined tree paths). Torn-write-safe via tmp + rename like
    every other artifact writer in the repo."""
    import os
    import tempfile

    flat = _flatten(params)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_drafter_params(path: str, like):
    """Load a ``save_drafter_params`` npz into the structure of
    ``like`` (a template param tree from the drafter model's init).
    Every leaf must be present with the template's exact shape — a
    drafter checkpoint from a different width/depth is a config error,
    not something to silently pad."""
    import jax.numpy as jnp

    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    template = _flatten(like)
    missing = sorted(set(template) - set(flat))
    extra = sorted(set(flat) - set(template))
    if missing or extra:
        raise ValueError(
            f"drafter checkpoint {path!r} does not match the drafter "
            f"architecture: missing={missing[:4]} extra={extra[:4]}")
    for k, tmpl in template.items():
        if flat[k].shape != tmpl.shape:
            raise ValueError(
                f"drafter checkpoint {path!r} leaf {k!r} has shape "
                f"{flat[k].shape}, drafter wants {tmpl.shape}")

    def rebuild(node, prefix=""):
        if isinstance(node, dict):
            return {k: rebuild(v, f"{prefix}/{k}" if prefix else str(k))
                    for k, v in node.items()}
        return jnp.asarray(flat[prefix], dtype=np.asarray(node).dtype)

    return rebuild(like)


def fit_drafter(model, params, drafter_model, drafter_params, prompts,
                *, gen_tokens: int = 64, steps: int = 300,
                lr: float = 3e-3, log=None):
    """Distill ``drafter_model`` onto ``model``'s greedy trajectories.

    ``prompts`` is ``[N, P]`` int32 — the traffic to fit against. The
    teacher generates ``gen_tokens`` greedy continuations (dense
    full-prefix forwards; O(L^2) but the fitting set is small), then
    the drafter minimizes hard-target cross-entropy on the generated
    region with a hand-rolled Adam. Fully deterministic: same teacher,
    prompts, and init produce bitwise-identical drafter params, which
    keeps spec-on serving replayable.

    Returns the fitted drafter param tree.
    """
    import jax
    import jax.numpy as jnp

    prompts = np.asarray(prompts, np.int32)
    n, plen = prompts.shape
    total = plen + gen_tokens
    max_len = getattr(drafter_model, "max_len", None)
    if max_len is not None and total > max_len:
        raise ValueError(
            f"fit window {total} exceeds drafter max_len {max_len}")

    @jax.jit
    def teacher_step(p, toks):
        lg = model.apply({"params": p}, toks, train=False)
        return jnp.argmax(lg[:, -1].astype(jnp.float32), -1).astype(
            jnp.int32)

    seqs = np.zeros((n, total), np.int32)
    seqs[:, :plen] = prompts
    cur = jnp.asarray(seqs)
    for i in range(plen, total):
        nxt = teacher_step(params, cur[:, :i])
        cur = cur.at[:, i].set(nxt)
    toks = cur

    def loss_fn(dp):
        lg = drafter_model.apply({"params": dp}, toks[:, :-1],
                                 train=False)
        tgt = toks[:, 1:]
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0]
        mask = (jnp.arange(total - 1)[None, :] >= plen - 1).astype(
            jnp.float32)
        return (nll * mask).sum() / mask.sum() / n

    @jax.jit
    def adam_step(dp, m, v, t):
        g = jax.grad(loss_fn)(dp)
        m = jax.tree_util.tree_map(
            lambda a, b: 0.9 * a + 0.1 * b, m, g)
        v = jax.tree_util.tree_map(
            lambda a, b: 0.999 * a + 0.001 * b * b, v, g)
        mh = jax.tree_util.tree_map(lambda a: a / (1 - 0.9 ** t), m)
        vh = jax.tree_util.tree_map(lambda a: a / (1 - 0.999 ** t), v)
        dp = jax.tree_util.tree_map(
            lambda p, a, b: p - lr * a / (jnp.sqrt(b) + 1e-8),
            dp, mh, vh)
        return dp, m, v

    mom = jax.tree_util.tree_map(jnp.zeros_like, drafter_params)
    vel = jax.tree_util.tree_map(jnp.zeros_like, drafter_params)
    dp = drafter_params
    for t in range(1, steps + 1):
        dp, mom, vel = adam_step(dp, mom, vel, jnp.float32(t))
        if log is not None and t % 100 == 0:
            log(f"fit_drafter step {t}/{steps}: "
                f"loss {float(loss_fn(dp)):.4f}")
    return dp
