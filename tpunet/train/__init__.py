from tpunet.train.state import TrainState, create_train_state, make_optimizer  # noqa: F401
from tpunet.train.steps import make_train_step, make_eval_step  # noqa: F401
from tpunet.train.metrics import Metrics, zeros_metrics, accumulate, summarize  # noqa: F401
from tpunet.train.loop import Trainer  # noqa: F401
