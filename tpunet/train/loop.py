"""Epoch-driven training loop with best-checkpoint tracking.

Mirrors the reference's main() shape (cifar10_mpi_mobilenet_224.py:52-252):
per-epoch [reshuffled sharded train pass -> full eval pass -> scheduler
tick -> rank-0 epoch log line -> best-accuracy tracking], then a final
save — re-built on jit/shardings: one XLA program per train step (which
internally augments, runs the model, all-reduces grads over the mesh and
updates Adam), device-resident metric accumulation, exact global metrics,
and crash-safe Orbax checkpoints with true resume (the reference restarts
from epoch 0, SURVEY.md section 5).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from tpunet.ckpt import Checkpointer
from tpunet.config import TrainConfig
from tpunet.data import (eval_batches, get_dataset, steps_per_epoch,
                         timed_batches, train_batches)
from tpunet.obs import JsonlSink, Observability, RunUnhealthyError
from tpunet.obs import flightrec
from tpunet.obs.perf import train_flops_per_unit
from tpunet.elastic import events as elastic_events
from tpunet.parallel import (batch_sharding, make_mesh, replicated_sharding,
                             shard_host_batch)
from tpunet.parallel.mesh import mesh_shape_dict
from tpunet.parallel.tp import rules_for, state_shardings, tree_shardings
from tpunet.train import metrics as M
from tpunet.train.state import create_train_state, lr_schedule
from tpunet.train.steps import (make_eval_step, make_lm_eval_step,
                                make_lm_train_step, make_train_step)
from tpunet.utils import Timer, epoch_line, log0
from tpunet.utils.logging import MetricsLogger, summary_lines
from tpunet.utils.preemption import PreemptionGuard
from tpunet.utils.prng import root_key, step_key


class Trainer:
    """Owns the mesh, state, jitted steps, and the epoch loop."""

    def __init__(self, cfg: TrainConfig, mesh=None, dataset=None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else make_mesh(cfg.mesh)
        ds = dataset if dataset is not None else get_dataset(cfg.data)
        self.train_x, self.train_y, self.test_x, self.test_y = ds
        self.spe = steps_per_epoch(len(self.train_x), cfg.data.batch_size)
        if self.spe == 0:
            raise ValueError("batch size larger than training set")

        self.is_lm = cfg.model.name in ("lm", "lm_pp")
        is_token_data = cfg.data.dataset in ("synthetic_lm", "text_lm")
        if self.is_lm != is_token_data:
            raise ValueError(
                f"model {cfg.model.name!r} and dataset "
                f"{cfg.data.dataset!r} are different families (the 'lm' "
                "model needs token data, e.g. --dataset synthetic_lm)")
        if self.is_lm and cfg.model.vocab_size != cfg.data.vocab_size:
            raise ValueError(
                f"model vocab {cfg.model.vocab_size} != data vocab "
                f"{cfg.data.vocab_size}; out-of-range tokens would be "
                "silently clamped by the embedding")
        state = create_train_state(
            cfg.model, cfg.optim, root_key(cfg.seed),
            image_size=cfg.data.image_size,
            steps_per_epoch=self.spe, epochs=cfg.epochs, mesh=self.mesh,
            seq_len=cfg.data.seq_len, allow_download=cfg.data.download)
        repl = replicated_sharding(self.mesh)
        bsh = batch_sharding(self.mesh)
        # Tensor parallelism: params (and, via mirrored tree paths, their
        # Adam moments) matching the model's TP path rules are sharded
        # over the 'model' mesh axis; everything else is replicated, which
        # is exactly the reference's DDP layout (README:77).
        # state_shardings is also the elastic re-mesh contract: a
        # resized world builds this against ITS mesh and the restore
        # re-shards every FSDP leaf onto the new data axis
        # (docs/elasticity.md).
        state_sh = state_shardings(
            state, cfg.model, self.mesh, zero1=cfg.mesh.zero1,
            fsdp=cfg.mesh.fsdp)
        if jax.process_count() > 1:
            try:
                self.state = jax.device_put(state, state_sh)
            except ValueError:
                # Older jax rejects device_put onto non-addressable
                # (multi-controller global mesh) shardings; a jitted
                # identity with pinned out_shardings reaches the same
                # layout — every process holds the identical host
                # state (deterministic same-seed init), which is
                # exactly the replicated-input contract jit assumes.
                self.state = jax.jit(lambda x: x,
                                     out_shardings=state_sh)(state)
        else:
            self.state = jax.device_put(state, state_sh)

        # out_shardings pinned: without it XLA may propagate shard_map
        # internals (e.g. a 'seq'-sharded pos-embed gradient) onto the
        # returned state, which would then mismatch in_shardings on the
        # next call.
        accum = cfg.optim.grad_accum
        if accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {accum}")
        if not 0.0 <= cfg.optim.ema_decay < 1.0:
            # decay >= 1 silently freezes the EMA at the random init and
            # eval/best-checkpoint would measure that forever.
            raise ValueError(f"ema_decay must be in [0, 1), got "
                             f"{cfg.optim.ema_decay}")
        if cfg.log_every_steps < 0:
            raise ValueError(f"log_every_steps must be >= 0, got "
                             f"{cfg.log_every_steps}")
        if cfg.data.mixup_alpha < 0 or cfg.data.cutmix_alpha < 0:
            raise ValueError("mixup/cutmix alphas must be >= 0")
        if self.is_lm and (cfg.data.mixup_alpha > 0
                           or cfg.data.cutmix_alpha > 0):
            raise ValueError("mixup/cutmix are image-family options; "
                             "the LM train step does not read them")
        if not 0.0 <= cfg.optim.warmup_epochs < cfg.epochs:
            # warmup >= the whole run would keep every step on the ramp
            # (base LR never reached, cosine horizon collapses to 1).
            raise ValueError(
                f"warmup_epochs ({cfg.optim.warmup_epochs}) must be in "
                f"[0, epochs={cfg.epochs})")
        if cfg.data.batch_size % accum:
            raise ValueError(
                f"batch size {cfg.data.batch_size} is not divisible by "
                f"grad_accum {accum}")
        ndata = self.mesh.shape.get("data", 1)
        if (cfg.data.batch_size // accum) % ndata:
            raise ValueError(
                f"microbatch {cfg.data.batch_size // accum} "
                f"(batch {cfg.data.batch_size} / grad_accum {accum}) is "
                f"not divisible by the data-axis size {ndata}")
        if (cfg.model.name in ("vit_pp", "lm_pp") and accum > 1
                and self.mesh.shape.get("pipe", 1) > 1):
            # Time-microbatching (accum) wraps stage-microbatching
            # (GPipe): each accum slice must still split into
            # pp_microbatches per data shard.
            npipe_mb = cfg.model.pp_microbatches
            per_shard = cfg.data.batch_size // accum // ndata
            if per_shard % npipe_mb:
                raise ValueError(
                    f"grad_accum x pipeline: per-data-shard microbatch "
                    f"{per_shard} (batch {cfg.data.batch_size} / accum "
                    f"{accum} / data {ndata}) is not divisible by "
                    f"pp_microbatches {npipe_mb}")
        # FSDP gathers params to their COMPUTE layout at step start: the
        # TP/PP spec (without the FSDP catch-alls) for model/pipe leaves,
        # replicated for the rest — tensor/pipeline compute sharding is
        # preserved; only the resting 'data' shard is gathered.
        gather_sh = None
        if cfg.mesh.fsdp:
            gather_sh = tree_shardings(
                state.params, self.mesh,
                rules_for(cfg.model, mesh=self.mesh))
        packed = cfg.data.pack_docs
        if packed:
            if cfg.data.dataset != "text_lm":
                raise ValueError(
                    f"--pack-docs packs text_lm documents; dataset is "
                    f"{cfg.data.dataset!r} (its labels are not segment "
                    "ids)")
            if not self.is_lm:
                raise ValueError("--pack-docs needs --model lm or "
                                 "lm_pp (the segment-masked attention "
                                 "paths)")
            if cfg.model.attention not in ("dense", "flash", "auto",
                                           "ulysses"):
                raise ValueError(
                    f"--pack-docs needs a segment-capable attention "
                    f"core (dense/flash/auto, or ulysses for packed x "
                    f"SP), got {cfg.model.attention!r} — ring's "
                    "state-merging core has no segment operands")
        train_fn = (make_lm_train_step(cfg.optim, cfg.model, self.mesh,
                                       gather_params=gather_sh,
                                       packed=packed)
                    if self.is_lm
                    else make_train_step(cfg.data, cfg.optim, cfg.model,
                                         self.mesh,
                                         gather_params=gather_sh))
        eval_fn = (make_lm_eval_step(cfg.model, self.mesh,
                                     gather_params=gather_sh,
                                     packed=packed) if self.is_lm
                   else make_eval_step(cfg.data, gather_params=gather_sh))
        self.train_step = jax.jit(
            train_fn,
            in_shardings=(state_sh, bsh, bsh, repl),
            out_shardings=(state_sh, repl),
            donate_argnums=0)
        self.eval_step = jax.jit(
            eval_fn,
            in_shardings=(state_sh, bsh, bsh, bsh))

        self._prefetcher = None
        if (cfg.data.native_loader and not cfg.eval_only
                and not cfg.data.pack_docs):
            # The native gather moves raw bytes per row, so uint8 image
            # rows and int32 token rows share the same path. Packed
            # datasets carry [B, T] segment ids in the label slot, which
            # the prefetcher's scalar-label ABI doesn't cover — numpy
            # path there.
            # The long-standing resume heap-corruption bug that used
            # to force a numpy-loader fallback here was root-caused
            # (flight-recorder evidence, runs/flightrec-repro-r7) to
            # buffer donation of orbax-restored state — nothing to do
            # with the prefetcher — and fixed at the source
            # (Checkpointer.restore_state re-materializes restored
            # arrays), so resumed runs keep the native path.
            from tpunet.data import native
            if native.available():
                local = cfg.data.batch_size // jax.process_count()
                self._prefetcher = native.NativePrefetcher(
                    self.train_x, self.train_y.astype(np.int32),
                    local)

        self._schedule = lr_schedule(cfg.optim, self.spe, cfg.epochs)
        # Observability (tpunet/obs/): per-step timing + stall split +
        # windowed profiling. Constructed before the Checkpointer so
        # checkpoint dispatch/wait can report into the same registry.
        self.obs = Observability(
            cfg.obs, profile_dir=cfg.profile_dir,
            checkpoint_dir=cfg.checkpoint.directory,
            unit="tokens" if self.is_lm else "examples",
            # resume keeps the persisted run_id, so the restored
            # stream continues the same fleet identity.
            resume=cfg.checkpoint.resume)
        if self.obs.enabled:
            # Config fingerprint joins runs of the same workload: the
            # run-history store and cross-run regression compare
            # (tpunet/obs/history/) only judge run N against run N-1
            # when the fingerprints match, and BENCH artifacts join to
            # training runs through the same hash.
            from tpunet.obs.history import train_fingerprint
            ident = self.obs.registry.identity()
            self.obs.registry.set_identity(
                **ident, config_fingerprint=train_fingerprint(cfg))
        from tpunet.models import num_params
        self.obs.set_flops_per_unit(train_flops_per_unit(
            cfg.model, cfg.data, n_params=num_params(state.params)))
        self.ckpt = Checkpointer(cfg.checkpoint, obs=self.obs)
        self.guard = PreemptionGuard(deadline_s=cfg.preempt_grace_s)
        # Fault injection (--chaos): armed process-globally so the
        # checkpointer's IO hooks reach the same injector; scoped to
        # this process index (host=H events address one gang member).
        self._chaos = None
        if cfg.chaos:
            from tpunet.elastic import chaos as chaos_mod
            self._chaos = chaos_mod.install(
                cfg.chaos, process_index=jax.process_index())
        # Elastic-agent context (TPUNET_ELASTIC_* env): generation
        # gauges for the fleet view, the previous incarnation's mesh
        # for the "recovered" record, and this incarnation's mesh
        # persisted for the NEXT one.
        self._elastic = elastic_events.agent_env()
        self._prev_mesh = None
        if self._elastic is not None:
            run_dir = cfg.checkpoint.directory
            self._prev_mesh = elastic_events.read_mesh(run_dir)
            if jax.process_index() == 0:
                elastic_events.write_mesh(run_dir,
                                          mesh_shape_dict(self.mesh))
            if self.obs.enabled:
                reg = self.obs.registry
                reg.gauge("elastic_generation").set(
                    self._elastic["generation"])
                reg.gauge("elastic_world_processes").set(
                    jax.process_count())
        self._watchdog_halt = None
        # Proactive checkpoint-and-evict (--evict-on-straggler): a
        # straggler-shaped alert on THIS replica requests the agreed
        # stop with an evict marker — the pod checkpoints now, the
        # elastic agent re-meshes without the slow host.
        self._evict_requested = None
        if (self.obs.watchdog is not None
                and cfg.obs.evict_on_straggler):
            def _evict(record):
                if self._evict_requested is None:
                    # Claim at ALERT time (first claim wins — several
                    # replicas' watchdogs may fire near-simultaneously
                    # under a pod-wide slowdown): the claimer is the
                    # evicted replica; everyone still requests the
                    # agreed stop so the pod checkpoints together.
                    claimed = elastic_events.write_evict_marker(
                        cfg.checkpoint.directory,
                        process_index=jax.process_index(),
                        host=elastic_events.agent_host(),
                        reason=str(record.get("reason", "straggler")),
                        detail=record)
                    print(f"[process {jax.process_index()}] EVICT "
                          f"{'claimed' if claimed else 'joined'} "
                          f"after watchdog alert: {record}",
                          flush=True)
                    self._evict_requested = record
                    self.guard.request()
            self.obs.watchdog.on_evict = _evict
        if jax.process_count() > 1 and self.obs.watchdog is not None:
            # Multi-host --halt-on-unhealthy: a fatal alert on any one
            # process must not raise there (the others would wedge in
            # their next collective). Route it through the preemption
            # guard instead — _stop_agreed's allgather then stops
            # every host at a step boundary with a partial-epoch save,
            # after which train() re-raises so the exit code still
            # says "unhealthy" (2), not "clean preemption" (0).
            def _halt(record):
                # print (not log0): the detecting host may not be the
                # coordinator, and its log is where the evidence goes.
                print(f"[process {jax.process_index()}] HALT requested "
                      f"by watchdog: {record}", flush=True)
                self._watchdog_halt = record
                self.guard.request()
            self.obs.watchdog.on_fatal = _halt
        self.global_step = 0
        self.start_epoch = 1
        self.best_acc = 0.0
        self.history: List[Dict[str, float]] = []
        self._hbm_attrib_pending = bool(cfg.obs.enabled
                                        and cfg.obs.hbm_attrib)
        if cfg.checkpoint.resume:
            self._try_resume()

    # ------------------------------------------------------------------

    def _pp_layout(self) -> np.ndarray:
        """[pipe, virtual] when the stacked params are stored in the
        interleaved schedule's chunk-PERMUTED order, else [0, 0] —
        persisted with the state so a resume under a different
        (schedule, pipe, virtual) fails loudly instead of silently
        reinterpreting a layer-scrambled stack
        (tpunet/parallel/pp.py interleaved_layer_order)."""
        il = (self.cfg.model.pp_schedule == "interleaved"
              and self.mesh.shape.get("pipe", 1) > 1)
        return np.asarray(
            [self.mesh.shape.get("pipe", 1), self.cfg.model.pp_virtual]
            if il else [0, 0], np.int32)

    def _payload(self, completed: bool = True) -> Dict:
        return {
            "state": self.state,
            "epoch": np.asarray(self.start_epoch, np.int32),
            # 0 marks a mid-epoch (preemption) save: resume re-runs that
            # epoch instead of skipping its remaining data (at-least-once
            # semantics; the restored step counter keeps the LR schedule
            # continuous either way).
            "completed": np.asarray(int(completed), np.int32),
            "global_step": np.asarray(self.global_step, np.int32),
            "best_acc": np.asarray(self.best_acc, np.float32),
            "pp_layout": self._pp_layout(),
        }

    def _try_resume(self) -> None:
        restored = self.ckpt.restore_state(self._payload())
        if restored is None:
            return
        got = [int(x) for x in np.asarray(restored.get(
            "pp_layout", np.zeros(2, np.int32)))]
        want = [int(x) for x in self._pp_layout()]
        if got != want:
            def name(lay):
                return ("gpipe/1f1b layout" if lay[0] == 0 else
                        f"interleaved pipe={lay[0]} virtual={lay[1]}")
            raise ValueError(
                f"checkpoint stack layout mismatch: saved with "
                f"{name(got)}, resuming with {name(want)} — the "
                "interleaved schedule stores chunk-permuted layer "
                "stacks, so resume with the same --pp-schedule/"
                "--mesh-pipe/--pp-virtual as the original run")
        self.state = restored["state"]
        completed = int(restored.get("completed", 1))
        self.start_epoch = int(restored["epoch"]) + (1 if completed else 0)
        self.global_step = int(restored["global_step"])
        self.best_acc = float(restored["best_acc"])
        flightrec.record("train", f"resume restored epoch="
                                  f"{int(restored['epoch'])} "
                                  f"step={self.global_step}")
        log0(f"Resumed from epoch {int(restored['epoch'])}"
             f"{'' if completed else ' (partial)'} "
             f"(best acc {self.best_acc:.4f})")

    # ------------------------------------------------------------------

    def _epoch_batches(self, epoch: int):
        cfg = self.cfg
        if self._prefetcher is not None:
            from tpunet.data.pipeline import host_index_sequence
            idx = host_index_sequence(
                len(self.train_x), global_batch=cfg.data.batch_size,
                seed=cfg.seed, epoch=epoch,
                process_index=jax.process_index(),
                process_count=jax.process_count())
            return self._prefetcher.iter_epoch(idx)
        return train_batches(
            self.train_x, self.train_y,
            global_batch=cfg.data.batch_size,
            seed=cfg.seed, epoch=epoch,
            process_index=jax.process_index(),
            process_count=jax.process_count())

    # Multi-host preemption polling period (steps). The agreement
    # collective blocks the host, so it runs every K steps, in lockstep
    # on all hosts; a preemption grace window is tens of seconds, far
    # longer than K steps. Env-overridable (TPUNET_STOP_POLL_STEPS) so
    # the chaos harness can exercise agreed stops inside tiny epochs
    # (docs/elasticity.md).
    STOP_POLL_STEPS = int(os.environ.get("TPUNET_STOP_POLL_STEPS", "16"))

    def _agree_stop(self, tag: str) -> bool:
        """Cross-host OR of the local stop flag. Routed through the
        coordination-service KV store (tpunet/parallel/dist.agree_any)
        because this runs CONCURRENTLY with the async checkpoint
        worker's orbax cross-host barriers — two XLA host collectives
        from two threads interleave differently per process and abort
        the transport (the gloo preamble crash the chaos evict leg
        reproduced). Allgather remains the no-coordination-service
        fallback, where no concurrent orbax barriers can exist."""
        from tpunet.parallel.dist import agree_any
        stop = agree_any(tag, self.guard.requested)
        if stop is None:
            from jax.experimental import multihost_utils
            import jax.numpy as jnp
            flags = multihost_utils.process_allgather(
                jnp.asarray(self.guard.requested))
            stop = bool(np.asarray(flags).any())
        if stop:
            self.guard.request()  # keep local flag consistent for train()
        return stop

    def _stop_agreed(self) -> bool:
        """Cross-host-agreed preemption decision. The signal flag is
        process-local; if hosts diverged on it, the ones still issuing
        the sharded train step would deadlock in its collectives and the
        multi-host Orbax save would wedge. All hosts agree in lockstep
        (every STOP_POLL_STEPS steps) and stop if ANY host was
        signalled — per-VM spot preemption hits workers too, not just
        the coordinator."""
        if jax.process_count() == 1:
            return self.guard.requested
        if self.global_step % self.STOP_POLL_STEPS:
            return False
        return self._agree_stop(f"stop/{self.global_step}")

    def _epoch_stop_agreed(self, epoch: int) -> bool:
        """Epoch-boundary stop agreement. The in-loop ``_stop_agreed``
        only polls every STOP_POLL_STEPS, so a signal landing in the
        final stretch of an epoch can leave hosts DIVERGED at the
        epoch boundary: the signalled host would take the partial-save
        path (a collective orbax save) while the rest enter eval —
        deadlock. One agreement per epoch, run by every host in
        lockstep right after the epoch, closes that hole."""
        if jax.process_count() == 1:
            return self.guard.requested
        return self._agree_stop(f"estop/{epoch}")

    def train_one_epoch(self, epoch: int) -> Dict[str, float]:
        cfg = self.cfg
        every = cfg.log_every_steps
        acc = None
        obs = self.obs
        # Hoisted once per epoch: the disabled path pays exactly one
        # branch per step, no spans, no timer objects, no wrapper
        # around the batch iterator.
        obs_hot = obs.hot
        obs.begin_epoch(epoch)
        batches = self._epoch_batches(epoch)
        if obs_hot:
            batches = timed_batches(
                batches, obs.observe_data_wait,
                wait_ctx=lambda: obs.span("tpunet/data_wait"))
            sync = lambda: jax.block_until_ready(self.state)  # noqa: E731
            step_timer = Timer()
        for bx, by in batches:
            if self._stop_agreed():
                break  # preemption: stop at a step boundary
            rng = step_key(cfg.seed, self.global_step)
            if self._hbm_attrib_pending:
                self._hbm_attrib_pending = False
                self._attribute_hbm_bytes(bx, by, rng)
            if obs_hot:
                # Profile-window edge check; the sync fence runs only
                # on the two steps where a window opens/closes. The
                # lap measures host-side dispatch wall time — under
                # saturated async dispatch that converges to device
                # step time; epoch totals are exact either way (the
                # end-of-epoch summarize() is the window-edge sync).
                obs.before_step(self.global_step, sync)
                step_timer.lap()
                if self._chaos is not None:
                    # Fault injection fires INSIDE the measured step
                    # window, host-side: SIGKILL/SIGTERM/slow-host
                    # land exactly where real faults strike — and an
                    # injected straggler delay shows up in step_time_s
                    # where the watchdog's stall detector looks.
                    self._chaos.step(self.global_step)
                with obs.step_span(self.global_step):
                    gx, gy = shard_host_batch(self.mesh, bx,
                                              by.astype(np.int32))
                    self.state, m = self.train_step(self.state, gx, gy,
                                                    rng)
                obs.observe_step(self.global_step, step_timer.lap())
            else:
                if self._chaos is not None:
                    self._chaos.step(self.global_step)
                gx, gy = shard_host_batch(self.mesh, bx,
                                          by.astype(np.int32))
                self.state, m = self.train_step(self.state, gx, gy, rng)
            acc = m if acc is None else M.accumulate(acc, m)
            self.global_step += 1
            if obs_hot and obs.profiler.running:
                # A window ending exactly at the epoch boundary must
                # close HERE, not on the next epoch's first step —
                # otherwise the trace bleeds across eval/checkpoint.
                obs.profiler.on_step(self.global_step, sync)
            if every and self.global_step % every == 0:
                # Opt-in per-step line (forces a device sync for the
                # metric values; per-epoch-only, like the reference,
                # when log_every_steps == 0).
                sm = M.summarize(m)
                # The loss is a host float here anyway — feed the
                # watchdog's NaN/spike detector at no extra sync cost.
                obs.observe_loss(self.global_step, sm["loss"])
                # The step just taken consumed optax's PRE-increment
                # count, i.e. schedule(global_step - 1) — print the LR
                # that actually produced this loss.
                lr = float(self._schedule(self.global_step - 1))
                log0(f"  step {self.global_step} "
                     f"loss {sm['loss']:.4f} acc {sm['accuracy']:.4f} "
                     f"lr {lr:.3e}")
        return M.summarize(acc if acc is not None else M.zeros_metrics())

    def _attribute_hbm_bytes(self, bx, by, rng) -> None:
        """--obs-hbm-attrib: once, before the first step, AOT-lower
        the train step and mirror the per-op-category decomposition of
        its cost-analysis HBM bytes into the hbm_bytes_per_image_*
        gauges (tpunet/obs/hlo_bytes.py). The extra lowering compiles
        nothing new when the persistent compile cache is warm; any
        failure is logged and training proceeds (attribution is
        observability, never a reason to stop a run)."""
        try:
            from tpunet.obs import hlo_bytes
            gx, gy = shard_host_batch(self.mesh, bx, by.astype(np.int32))
            compiled = self.train_step.lower(
                self.state, gx, gy, rng).compile()
            per_chip = max(1, self.cfg.data.batch_size
                           // jax.device_count())
            self.obs.set_hbm_breakdown(hlo_bytes.per_image_breakdown(
                compiled.as_text(), per_chip))
        except Exception as e:  # pragma: no cover - backend-specific
            log0(f"hbm byte attribution failed: {e}")

    def current_lr(self) -> float:
        """The LR the NEXT step will use (host-side schedule lookup)."""
        return float(self._schedule(self.global_step))

    def evaluate_checkpoint(self) -> Dict[str, float]:
        """--eval-only: load the saved weights and run one evaluation
        pass — the best-params checkpoint when present (what inference
        serves), else the last full train state."""
        # Eval-only runs have no step loop to drive the windowed
        # profiler, but a configured --profile-dir still means "trace
        # this run": open the trace here; Trainer.close() (main.py's
        # finally) stops and flushes it.
        prof = self.obs.profiler
        if prof.active and not prof.running:
            prof.on_step(prof.start_step)
        best = self.ckpt.restore_best({
            "params": self.state.params,
            "batch_stats": self.state.batch_stats})
        if best is not None:
            kw = dict(params=best["params"],
                      batch_stats=best["batch_stats"])
            if self.cfg.optim.ema_decay > 0:
                # the best checkpoint already holds the EMA pair, and
                # evaluate() reads the ema_* fields when EMA is on
                kw.update(ema_params=best["params"],
                          ema_batch_stats=best["batch_stats"])
            self.state = self.state.replace(**kw)
        elif self.ckpt.latest_step() is not None:
            self._try_resume()
        else:
            raise FileNotFoundError(
                f"no checkpoint under {self.cfg.checkpoint.directory!r} "
                "(need best/ or state/ to --eval-only)")
        return self.evaluate()

    def evaluate(self) -> Dict[str, float]:
        cfg = self.cfg
        state = self.state
        if cfg.optim.ema_decay > 0:
            # Evaluate the EMA weights + EMA BN stats as a pair (what
            # the best-checkpoint saves). Both mirror their live trees
            # shape-for-shape and shard-for-shard (tp.py FSDP_RULES),
            # so in_shardings still match.
            state = state.replace(params=state.ema_params,
                                  batch_stats=state.ema_batch_stats)
        acc = None
        with self.obs.span("tpunet/eval"):
            for bx, by, bm in eval_batches(
                    self.test_x, self.test_y,
                    global_batch=cfg.data.effective_eval_batch_size,
                    process_index=jax.process_index(),
                    process_count=jax.process_count()):
                gx, gy, gm = shard_host_batch(
                    self.mesh, bx, by.astype(np.int32), bm)
                m = self.eval_step(state, gx, gy, gm)
                acc = m if acc is None else M.accumulate(acc, m)
        return M.summarize(acc if acc is not None else M.zeros_metrics())

    # ------------------------------------------------------------------

    def train(self) -> List[Dict[str, float]]:
        cfg = self.cfg
        log0(f"Train samples: {len(self.train_x)}")
        log0(f"Test samples: {len(self.test_x)}")
        from tpunet.models import num_params
        log0(f"Total parameters: {num_params(self.state.params)}")
        log0("Host loader: " + ("native C++ prefetcher"
                                if self._prefetcher is not None else "numpy"))
        log0("Starting training...")
        flightrec.record("train", "starting training loader="
                         + ("native" if self._prefetcher is not None
                            else "numpy"))
        log0("")
        metrics_log = MetricsLogger(cfg.checkpoint.directory,
                                    resume=cfg.checkpoint.resume)
        # obs records (obs_epoch / obs_step) share the run's
        # metrics.jsonl; MetricsLogger already restricts writes to the
        # coordinator.
        self.obs.add_sink(JsonlSink(metrics_log))
        if (self.obs.enabled and self._elastic is not None
                and self._elastic["generation"] > 0):
            # A re-meshed incarnation: the recovery record that pairs
            # with the agent's shrink/grow/restart — same run_id, the
            # NEW mesh, and the restore stamp that proves which
            # checkpoint carried the run across (docs/elasticity.md).
            self.obs.registry.emit(
                "obs_elastic", elastic_events.build_elastic_record(
                    "recovered",
                    generation=self._elastic["generation"],
                    new_world=jax.process_count(),
                    old_mesh=self._prev_mesh,
                    new_mesh=mesh_shape_dict(self.mesh),
                    epoch=self.start_epoch, step=self.global_step))
        # The PLAIN epoch records below bypass Registry.emit, so stamp
        # them here: without identity the fleet aggregator would file
        # them under a junk per-file stream instead of this run's.
        identity = self.obs.registry.identity()
        total = Timer()
        self.guard.install()
        try:
            for epoch in range(self.start_epoch, cfg.epochs + 1):
                timer = Timer()
                train_m = self.train_one_epoch(epoch)
                train_secs = timer.elapsed()
                # Watchdog loss checks run BEFORE the hard NaN guard:
                # the obs_alert record lands in metrics.jsonl (and the
                # live exporters) even when the guard below aborts the
                # run, so the post-mortem explains itself. Under
                # --halt-on-unhealthy this raises RunUnhealthyError.
                self.obs.observe_loss(self.global_step, train_m["loss"])
                if not np.isfinite(train_m["loss"]):
                    # Failure detection (SURVEY.md section 5: the
                    # reference has none — a NaN run would burn its full
                    # SLURM walltime producing garbage). Stop BEFORE
                    # save_state so the resume chain keeps the last
                    # finite epoch, not the poisoned weights — and make
                    # that checkpoint durable first (saves are async;
                    # raising past an uncommitted save would break the
                    # message's promise).
                    self.ckpt.wait()
                    raise FloatingPointError(
                        f"non-finite train loss ({train_m['loss']}) at "
                        f"epoch {epoch}; the last completed checkpoint "
                        f"is still finite — resume from it with a lower "
                        f"--lr or with --clip-norm")
                if self._epoch_stop_agreed(epoch):
                    if self.guard.escalated:
                        # Second SIGTERM inside the grace window: the
                        # platform is saying NOW. Best-effort abandon:
                        # no save, no durability wait — a save that
                        # gets SIGKILLed mid-write is strictly worse
                        # than resuming from the last intact
                        # checkpoint (which is exactly what --resume
                        # does).
                        flightrec.record(
                            "train", f"escalated preemption epoch="
                                     f"{epoch}")
                        log0(f"Second preemption signal at epoch "
                             f"{epoch} (step {self.global_step}); "
                             "abandoning checkpoint work and exiting "
                             "immediately")
                        self.start_epoch = epoch
                        self.ckpt.abandon()
                        break
                    # Preempted mid-epoch: persist the advanced state,
                    # marked partial so --resume re-runs this epoch's
                    # remaining data instead of skipping it.
                    flightrec.record("train", f"preemption epoch="
                                              f"{epoch}")
                    if self._evict_requested is not None:
                        # The agreed stop is an EVICT (marker already
                        # claimed at alert time); emit the
                        # obs_elastic breadcrumb that explains it
                        # (record-first: the straggler obs_alert is
                        # already in the stream).
                        if self.obs.enabled:
                            self.obs.registry.emit(
                                "obs_elastic",
                                elastic_events.build_elastic_record(
                                    "evict_requested",
                                    cause=str(
                                        self._evict_requested.get(
                                            "reason", "straggler")),
                                    epoch=epoch,
                                    step=self.global_step,
                                    detail=self._evict_requested))
                    log0(f"Preemption requested at epoch {epoch} (step "
                         f"{self.global_step}); "
                         + ("saving state and exiting"
                            if cfg.checkpoint.save_last else
                            "state NOT saved (checkpoint.save_last is "
                            "off) — exiting"))
                    self.start_epoch = epoch
                    self.ckpt.save_state(epoch,
                                         self._payload(completed=False))
                    # Self-describing history: the eval pass was skipped,
                    # so resumed metrics.jsonl readers can tell this row
                    # apart from a completed epoch (VERDICT r1 item 10).
                    metrics_log.log({
                        **identity,
                        "epoch": epoch, "partial": True,
                        "step": self.global_step,
                        "seconds": timer.elapsed(),
                        "train_loss": train_m["loss"],
                        "train_accuracy": train_m["accuracy"],
                    })
                    self.obs.end_epoch(
                        epoch=epoch, step=self.global_step,
                        units=train_m["count"],
                        train_seconds=train_secs, partial=True)
                    if self._watchdog_halt is not None:
                        # The "preemption" was the watchdog's agreed
                        # multi-host halt: the partial state is saved,
                        # now make the exit say UNHEALTHY — an
                        # orchestrator that auto-requeues preemptions
                        # must not silently restart a sick run.
                        self.ckpt.wait()
                        raise RunUnhealthyError(
                            f"run unhealthy (agreed multi-host halt): "
                            f"{self._watchdog_halt}; partial state "
                            f"saved at epoch {epoch}")
                    break
                test_m = self.evaluate()
                secs = timer.elapsed()
                log0(epoch_line(epoch, cfg.epochs, secs,
                                train_m["loss"], train_m["accuracy"],
                                test_m["loss"], test_m["accuracy"]))
                record = {
                    **identity,
                    "epoch": epoch, "seconds": secs,
                    "step": self.global_step,
                    # throughput over the epoch (eval pass included),
                    # in each family's metric unit: images/sec for the
                    # vision models (comparable with BASELINE.md's
                    # derived img/s), next-token predictions/sec for
                    # the LM (its metric count is B*(T-1) per batch).
                    ("tokens_per_sec" if self.is_lm else
                     "examples_per_sec"):
                        round(train_m["count"] / secs, 2),
                    "train_loss": train_m["loss"],
                    "train_accuracy": train_m["accuracy"],
                    "test_loss": test_m["loss"],
                    "test_accuracy": test_m["accuracy"],
                }
                self.history.append(record)
                metrics_log.log(record)
                if test_m["accuracy"] > self.best_acc:
                    self.best_acc = test_m["accuracy"]
                    # With EMA on, the test accuracy was measured on the
                    # EMA weights + EMA BN stats — save that pair (what
                    # inference loads).
                    ema_on = cfg.optim.ema_decay > 0
                    lay = self._pp_layout()
                    self.ckpt.save_best({
                        "params": (self.state.ema_params if ema_on
                                   else self.state.params),
                        "batch_stats": (self.state.ema_batch_stats
                                        if ema_on
                                        else self.state.batch_stats),
                    }, meta={
                        "model": cfg.model.name,
                        "pp_schedule": cfg.model.pp_schedule,
                        "pp_layout_pipe": int(lay[0]),
                        "pp_layout_virtual": int(lay[1]),
                    })
                self.start_epoch = epoch
                self.ckpt.save_state(epoch, self._payload())
                # After the save dispatches so this epoch's own
                # checkpoint shows in its cumulative ckpt counters.
                self.obs.end_epoch(
                    epoch=epoch, step=self.global_step,
                    units=train_m["count"], train_seconds=train_secs,
                    eval_seconds=secs - train_secs)
            else:
                # Every epoch completed (no preemption/evict break):
                # tell the elastic agents the run is DONE, not
                # preempted — without this a supervising agent would
                # faithfully relaunch a finished run.
                if self._elastic is not None \
                        and jax.process_index() == 0:
                    elastic_events.mark_done(cfg.checkpoint.directory)
        finally:
            self.guard.uninstall()
        log0("")
        for line in summary_lines(self.best_acc, total.elapsed()):
            log0(line)
        if self.guard.escalated:
            self.ckpt.abandon()
        else:
            # Durability barrier, bounded by whatever remains of the
            # preemption grace window (unbounded on a normal exit or
            # without --preempt-grace-s).
            self.ckpt.wait(timeout=self.guard.remaining())
        return self.history

    def close(self) -> None:
        # Each cleanup independent (nested finally): a failing
        # checkpoint flush cannot skip the profiler flush or the
        # prefetcher shutdown, or vice versa.
        try:
            self.obs.close(lambda: jax.block_until_ready(self.state))
        finally:
            try:
                if self._prefetcher is not None:
                    self._prefetcher.close()
                    self._prefetcher = None
            finally:
                self.ckpt.close()
