"""Globally-exact metrics as psum-able sums.

The reference accumulates loss*batch and correct counts per rank, then
all-reduces only the losses — accuracy stays a rank-local approximation
(cifar10_mpi_mobilenet_224.py:181-196,216-224). Here every metric is a
(loss_sum, correct, count) triple of *global* sums: reductions happen
inside the jitted step over the globally-sharded batch, so XLA inserts
the cross-device psum and all three numbers are exact on any mesh.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

Metrics = Dict[str, jax.Array]


def from_batch(loss_sum, correct, count) -> Metrics:
    return {
        "loss_sum": jnp.asarray(loss_sum, jnp.float32),
        "correct": jnp.asarray(correct, jnp.float32),
        "count": jnp.asarray(count, jnp.float32),
    }


def zeros_metrics() -> Metrics:
    return from_batch(0.0, 0.0, 0.0)


def accumulate(acc: Metrics, new: Metrics) -> Metrics:
    return jax.tree_util.tree_map(jnp.add, acc, new)


def summarize(acc: Metrics) -> Dict[str, float]:
    """Device scalars -> python floats {loss, accuracy, count}."""
    count = max(float(acc["count"]), 1.0)
    return {
        "loss": float(acc["loss_sum"]) / count,
        "accuracy": float(acc["correct"]) / count,
        "count": float(acc["count"]),
    }
