"""Train state and optimization stack.

Reference optimization stack (cifar10_mpi_mobilenet_224.py:147-149):
CrossEntropyLoss + Adam(lr=1e-4) + StepLR(step_size=10, gamma=0.1), with
BatchNorm statistics carried by the model. Here the whole thing is one
pytree (params, batch_stats, optimizer state, step) updated by a pure
function, and StepLR becomes an optax piecewise-constant schedule over
*steps* (epoch boundaries x steps_per_epoch).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from tpunet.config import ModelConfig, OptimConfig
from tpunet.models import create_model, init_variables
from tpunet.models.convert import load_pretrained


class TrainState(train_state.TrainState):
    """flax TrainState + BatchNorm running statistics + optional
    model-state EMA (both {} when ema_decay == 0). The EMA covers the
    BN running statistics as well as the params — evaluating EMA
    weights against live running stats would pair mismatched
    normalization with the weights (the reason torch's swa_utils
    requires an update_bn pass; timm's ModelEmaV2 EMAs the whole
    state_dict, which is the scheme here). Evaluation and the
    best-checkpoint use the EMA pair."""

    batch_stats: Any = None
    ema_params: Any = None
    ema_batch_stats: Any = None


def lr_schedule(cfg: OptimConfig, steps_per_epoch: int, epochs: int):
    """Step-indexed learning-rate schedule.

    ``schedule="step"`` is the reference's StepLR(step_size, gamma)
    (cifar10_mpi_mobilenet_224.py:149); "cosine" decays to 0 over the
    remaining steps; "constant" holds the base rate. A linear warmup of
    ``warmup_epochs`` (fractional epochs allowed) composes with any of
    them — the base schedule's clock starts when warmup ends
    (optax.join_schedules offsets the count)."""
    total = steps_per_epoch * epochs
    warm = int(round(cfg.warmup_epochs * steps_per_epoch))
    if cfg.schedule == "step":
        boundaries = {
            e * steps_per_epoch: cfg.gamma
            for e in range(cfg.step_size_epochs, epochs + 1,
                           cfg.step_size_epochs)
        }
        base = (optax.piecewise_constant_schedule(cfg.learning_rate,
                                                  boundaries)
                if boundaries else optax.constant_schedule(cfg.learning_rate))
    elif cfg.schedule == "cosine":
        base = optax.cosine_decay_schedule(cfg.learning_rate,
                                           max(1, total - warm))
    elif cfg.schedule == "constant":
        base = optax.constant_schedule(cfg.learning_rate)
    else:
        raise ValueError(f"unknown schedule {cfg.schedule!r}; "
                         "expected step|cosine|constant")
    if warm > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, cfg.learning_rate, warm), base],
            [warm])
    return base


def make_optimizer(cfg: OptimConfig, steps_per_epoch: int,
                   epochs: int) -> optax.GradientTransformation:
    schedule = lr_schedule(cfg, steps_per_epoch, epochs)
    if cfg.name == "adam" and cfg.weight_decay == 0.0:
        tx = optax.adam(schedule, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps)
    elif cfg.name in ("adam", "adamw"):
        tx = optax.adamw(schedule, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps,
                         weight_decay=cfg.weight_decay)
    elif cfg.name == "sgd":
        tx = optax.sgd(schedule, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    if cfg.clip_norm > 0:
        # Global-norm clip BEFORE the optimizer (the torch idiom
        # clip_grad_norm_-then-step); moment path rules still match —
        # the Adam state just nests one level deeper in the chain.
        tx = optax.chain(optax.clip_by_global_norm(cfg.clip_norm), tx)
    return tx


def create_train_state(model_cfg: ModelConfig, optim_cfg: OptimConfig,
                       rng: jax.Array, *, image_size: int,
                       steps_per_epoch: int, epochs: int,
                       mesh=None, seq_len: int = 16,
                       allow_download: bool = True) -> TrainState:
    """Build model variables (optionally overlaying converted pretrained
    torch weights, reference :137-139) and the optimizer state.

    ``mesh`` is forwarded to the model registry for models whose
    attention is sequence-parallel; ``batch_stats`` is empty for models
    without BatchNorm (the ViT family).
    """
    model = create_model(model_cfg, mesh=mesh)
    # Models that run shard_map internally constrain the init batch:
    # ring attention shards it over 'data'; the pipeline additionally
    # splits the local batch into microbatches. Everything else
    # initializes with batch 1.
    init_batch = 1
    if mesh is not None:
        if (model_cfg.name in ("vit_pp", "lm_pp")
                and mesh.shape.get("pipe", 1) > 1):
            init_batch = mesh.shape["data"] * model_cfg.pp_microbatches
        elif model_cfg.attention in ("ring", "ulysses"):
            init_batch = mesh.shape["data"]
    variables = init_variables(model, rng, image_size=image_size,
                               batch_size=init_batch, seq_len=seq_len)
    if model_cfg.pretrained_path:
        if model_cfg.name != "mobilenet_v2":
            raise ValueError(
                "pretrained_path converts torchvision MobileNetV2 "
                f"state_dicts only; model is {model_cfg.name!r}")
        path = model_cfg.pretrained_path
        if path == "auto":
            # Resolve/download AFTER the model check above (no wasted
            # fetch for non-MobileNet models). Under tpunet/main.py's
            # process-0 gate this is the reference's rank-0 + barrier
            # download dance (:93-102).
            from tpunet.data.download import ensure_mobilenet_v2_weights
            path = ensure_mobilenet_v2_weights(download=allow_download)
        variables = load_pretrained(path, variables,
                                    num_classes=model_cfg.num_classes)
    tx = make_optimizer(optim_cfg, steps_per_epoch, epochs)
    params = variables["params"]
    stats = variables.get("batch_stats", {})
    ema_on = optim_cfg.ema_decay > 0
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
    return TrainState.create(
        apply_fn=model.apply,
        params=params,
        batch_stats=stats,
        # EMA starts AT the initial state (torch.optim.swa_utils
        # convention); {} when disabled so the pytree stays minimal.
        ema_params=copy(params) if ema_on else {},
        ema_batch_stats=copy(stats) if ema_on else {},
        tx=tx,
    )
