"""Jitted train / eval steps.

The reference's hot loop (cifar10_mpi_mobilenet_224.py:173-185) is:
h2d copy -> zero_grad -> DDP forward -> CE loss -> backward (bucketed
NCCL allreduce hooks) -> Adam step -> metric accumulation. Here the
entire iteration — on-device augmentation, forward, loss, backward,
cross-device gradient reduction, optimizer update, metric sums — is ONE
jitted XLA program per step; the gradient all-reduce is inserted by XLA
from the sharding layout (batch on the 'data' mesh axis, params
replicated) rather than by framework hooks.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpunet.config import DataConfig, ModelConfig, OptimConfig
from tpunet.data.augment import (make_eval_preprocess, make_train_augment,
                                 mixup_cutmix)
from tpunet.train import metrics as M
from tpunet.train.state import TrainState


def _ce_loss(logits, targets, smoothing: float):
    """Per-example/token CE, optionally label-smoothed (StepLR stack's
    CrossEntropyLoss analogue; shared by the image and LM steps)."""
    if smoothing > 0:
        return optax.softmax_cross_entropy(
            logits, optax.smooth_labels(
                jax.nn.one_hot(targets, logits.shape[-1]), smoothing))
    return optax.softmax_cross_entropy_with_integer_labels(logits, targets)


def _aux_term(mutated, aux_weight: float):
    """Weighted sum of the MoE load-balance terms sown into 'losses'
    (0.0 when absent/unweighted) — the ONE place the aux rule lives."""
    aux_terms = jax.tree_util.tree_leaves(mutated.get("losses", {}))
    if aux_terms and aux_weight > 0:
        return aux_weight * sum(aux_terms)
    return 0.0


def _with_aux(loss, mutated, aux_weight: float):
    """Add weighted MoE load-balance terms sown into 'losses'."""
    return loss + _aux_term(mutated, aux_weight)


def _steps_from_micro(micro: Callable, accum: int, mesh,
                      gather_params=None, ema_decay: float = 0.0,
                      count_fn: Optional[Callable] = None) -> Callable:
    """Lift micro(params, batch_stats, apply_fn, x, y, rng) ->
    (grads, new_stats, metrics) into train_step(state, x, y, rng).

    accum == 1: one microbatch IS the batch (no scan overhead).
    accum > 1: the global batch is split into `accum` equal microbatches
    scanned *in time* — gradients averaged (mean of equal-sized means ==
    the full-batch mean), BatchNorm stats threaded through microbatches
    (torch semantics: stats update every forward), ONE optimizer update.
    ``count_fn`` (packed sequences): microbatch example counts are
    UNEQUAL (valid-target counts vary with packing), so the GLOBAL
    valid-target count ``count_fn(y)`` is computed up front and passed
    to the micro as ``grad_norm=(total, accum)`` — the micro normalizes
    its CE gradient by the global count (sum of microbatch grads then
    IS the full-batch mean) and any count-independent terms (MoE aux
    loss) by 1/accum (equal weighting).  Scaling whole microbatch
    gradients by their counts instead would bias count-independent
    terms toward fuller microbatches.
    Activation memory drops by ~1/accum; the XLA program stays static.
    The split is STRIDED (microbatch i = rows i, i+accum, ...): under
    the P('data') batch layout a contiguous split would move most rows
    off their home device every step, while the strided split maps each
    device's contiguous rows exactly onto its shard of every microbatch
    — zero resharding traffic. The partition is irrelevant to the math
    (the epoch shuffle already randomized row order).

    gather_params (the FSDP path): params are all-gathered ONCE at step
    start to ``gather_params`` — a params-tree of NamedShardings giving
    each leaf its COMPUTE layout: the TP/PP spec for model/pipe-sharded
    leaves (tensor/pipeline compute sharding is preserved, only the
    FSDP 'data' shard is gathered), replicated for the rest. Left to
    sharding propagation instead, GSPMD pushes the weight shards into
    attention activations and falls back to 'involuntary full
    rematerialization' reshards. The constraint's transpose reshards
    each weight's gradient straight back to its 'data' shard, and the
    Adam update then runs on 1/N-sized moment shards — sharded state,
    DP/TP/PP-layout compute.
    """

    # jax.named_scope labels below cost nothing at runtime (they apply
    # at trace time) but carry through to HLO op names, so xprof traces
    # attribute device time to fwd/bwd vs optimizer vs EMA phases.
    def finish(state, grads, stats):
        with jax.named_scope("tpunet_optimizer"):
            state = state.apply_gradients(grads=grads, batch_stats=stats)
        if ema_decay > 0:
            # EMA tracks the POST-update params AND the BN running
            # stats (evaluating EMA weights against live stats would
            # mismatch normalization); eval/best-ckpt read the pair.
            ema = lambda old, new: jax.tree_util.tree_map(
                lambda e, p: ema_decay * e + (1.0 - ema_decay) * p,
                old, new)
            with jax.named_scope("tpunet_ema"):
                state = state.replace(
                    ema_params=ema(state.ema_params, state.params),
                    ema_batch_stats=ema(state.ema_batch_stats,
                                        state.batch_stats))
        return state

    def train_step(state: TrainState, x, y, rng):
        params = state.params
        if gather_params is not None:
            params = jax.lax.with_sharding_constraint(params, gather_params)

        if accum == 1:
            with jax.named_scope("tpunet_fwd_bwd"):
                grads, stats, m = micro(params, state.batch_stats,
                                        state.apply_fn, x, y, rng)
            return finish(state, grads, stats), m

        mb = x.shape[0] // accum
        xs = x.reshape(mb, accum, *x.shape[1:]).swapaxes(0, 1)
        ys = y.reshape(mb, accum, *y.shape[1:]).swapaxes(0, 1)
        if mesh is not None:
            sh = lambda a: jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(None, "data")))
            xs, ys = sh(xs), sh(ys)
        rngs = jax.random.split(rng, accum)
        total = count_fn(y) if count_fn is not None else None

        def body(carry, inp):
            stats, gsum, msum = carry
            mx, my, mr = inp
            if count_fn is not None:
                grads, stats, m = micro(params, stats, state.apply_fn,
                                        mx, my, mr,
                                        grad_norm=(total, accum))
            else:
                grads, stats, m = micro(params, stats, state.apply_fn,
                                        mx, my, mr)
            gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
            return (stats, gsum, M.accumulate(msum, m)), None

        gzero = jax.tree_util.tree_map(jnp.zeros_like, state.params)
        with jax.named_scope("tpunet_fwd_bwd"):
            (stats, gsum, msum), _ = jax.lax.scan(
                body, (state.batch_stats, gzero, M.zeros_metrics()),
                (xs, ys, rngs))
        if count_fn is not None:
            grads = gsum        # micro already normalized globally
        else:
            grads = jax.tree_util.tree_map(lambda g: g / accum, gsum)
        return finish(state, grads, stats), msum

    return train_step


def make_train_step(data_cfg: DataConfig,
                    optim_cfg: OptimConfig,
                    model_cfg: Optional[ModelConfig] = None,
                    mesh=None, gather_params=None) -> Callable:
    """Build train_step(state, images_u8, labels, rng) -> (state, metrics).

    ``images_u8`` is the raw (global_batch, 32, 32, 3) uint8 batch;
    augmentation runs inside the step (fused by XLA with the forward).
    With optim_cfg.grad_accum > 1 the batch is scanned as microbatches;
    ``gather_params`` is the FSDP compute-layout sharding tree (see
    _steps_from_micro).
    """
    augment = make_train_augment(data_cfg)
    smoothing = optim_cfg.label_smoothing
    aux_weight = model_cfg.moe_aux_weight if model_cfg is not None else 0.0
    mixing = data_cfg.mixup_alpha > 0 or data_cfg.cutmix_alpha > 0

    def micro(params, batch_stats, apply_fn, images_u8, labels, rng):
        if mixing:
            aug_rng, dropout_rng, mix_rng = jax.random.split(rng, 3)
        else:
            # 2-way split when not mixing: keeps the augment/dropout
            # streams (and thus seed-for-seed runs) identical to
            # configs that predate the mixup option.
            aug_rng, dropout_rng = jax.random.split(rng)
        # Named scope: the on-device augmentation gets its own bucket
        # in the byte/time attributions (tpunet/obs/hlo_bytes.py) —
        # round 5 found ~20% of the step hiding here, so it must not
        # blur into the generic fwd/elementwise categories.
        with jax.named_scope("tpunet_augment"):
            images = augment(aug_rng, images_u8)
            if mixing:
                images, labels_b, lam = mixup_cutmix(
                    mix_rng, images, labels,
                    data_cfg.mixup_alpha, data_cfg.cutmix_alpha)

        def loss_fn(params):
            # mutable=["batch_stats"] is harmless for models without
            # BatchNorm (ViT): the mutated collection comes back empty.
            # "losses" carries MoE load-balance terms sown by MoeMlp.
            logits, mutated = apply_fn(
                {"params": params, "batch_stats": batch_stats},
                images, train=True,
                rngs={"dropout": dropout_rng},
                mutable=["batch_stats", "losses"])
            ce = _ce_loss(logits, labels, smoothing)
            if mixing:
                # Convex label combination; accuracy below stays vs the
                # PRIMARY label (standard mixup reporting).
                ce = lam * ce + (1.0 - lam) * _ce_loss(logits, labels_b,
                                                       smoothing)
            loss = _with_aux(ce.mean(), mutated, aux_weight)
            return loss, (logits, mutated.get("batch_stats", {}))

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        n = labels.shape[0]
        correct = jnp.sum(jnp.argmax(logits, -1) == labels)
        return grads, new_stats, M.from_batch(loss * n, correct, n)

    return _steps_from_micro(micro, max(1, optim_cfg.grad_accum), mesh,
                             gather_params=gather_params,
                             ema_decay=optim_cfg.ema_decay)


def _packed_target_weights(segs):
    """[B, T-1] float weights for next-token prediction under packing:
    a target is valid iff it continues the SAME document (segment id
    unchanged) and is not padding (id 0) — no prediction crosses a
    document boundary or lands on pad."""
    return ((segs[:, 1:] == segs[:, :-1])
            & (segs[:, 1:] != 0)).astype(jnp.float32)


def make_lm_train_step(optim_cfg: OptimConfig,
                       model_cfg: ModelConfig,
                       mesh=None, gather_params=None,
                       packed: bool = False) -> Callable:
    """train_step(state, tokens, labels, rng) -> (state, metrics) for
    the LM family: targets are the input shifted by one; metrics count
    next-token predictions (accuracy ~0.8 is ceiling on the synthetic
    bigram data, tpunet/data/lm.py). ``packed=True``: ``labels``
    carries [B, T] segment ids (tpunet/data/lm.py text_lm_packed) —
    attention is segment-masked inside the model and the loss/metrics
    drop cross-document and padding targets.

    With ``--vocab-ce`` resolving to "sharded" (auto: a mesh 'model'
    axis > 1 dividing the vocab) the model returns final-LN hidden
    states and the CE runs vocab-sharded against the tied embedding —
    the replicated [B, T, V] float32 logits never materialize
    (tpunet/ops/vocab_ce.py)."""
    aux_weight = model_cfg.moe_aux_weight
    smoothing = optim_cfg.label_smoothing
    from tpunet.ops.vocab_ce import resolve_vocab_ce, vocab_parallel_ce
    sharded_ce = (resolve_vocab_ce(model_cfg.vocab_ce, mesh,
                                   model_cfg.vocab_size) == "sharded")

    def micro(params, batch_stats, apply_fn, tokens, labels, rng,
              grad_norm=None):
        segs = labels if packed else None

        def loss_fn(params):
            kwargs = {"segment_ids": segs} if packed else {}
            tgt = tokens[:, 1:]
            if sharded_ce:
                h, mutated = apply_fn(
                    {"params": params, "batch_stats": batch_stats},
                    tokens, train=True, return_hidden=True,
                    rngs={"dropout": rng},
                    mutable=["batch_stats", "losses"], **kwargs)
                ce, hit = vocab_parallel_ce(
                    h[:, :-1], params["embed"]["embedding"], tgt,
                    mesh, smoothing=smoothing)
            else:
                logits, mutated = apply_fn(
                    {"params": params, "batch_stats": batch_stats},
                    tokens, train=True,
                    rngs={"dropout": rng},
                    mutable=["batch_stats", "losses"], **kwargs)
                lg = logits[:, :-1]
                ce = _ce_loss(lg, tgt, smoothing)
                hit = (jnp.argmax(lg, -1) == tgt).astype(jnp.float32)
            aux = _aux_term(mutated, aux_weight)
            if packed:
                wt = _packed_target_weights(segs)
                ce_sum = jnp.sum(ce * wt)
                n_valid = jnp.maximum(jnp.sum(wt), 1.0)
                if grad_norm is None:
                    loss = ce_sum / n_valid + aux
                    loss_sum = ce_sum + aux * n_valid
                else:
                    # Grad-accum: CE over the GLOBAL valid-target count
                    # and the count-independent aux term over 1/accum,
                    # so plain summation of microbatch grads restores
                    # the full-batch CE mean + equal-weighted aux mean
                    # (see _steps_from_micro's count_fn contract). The
                    # METRIC weights aux the same way: summed loss_sums
                    # divided by the total count give exactly
                    # CE_global_mean + mean_i(aux_i) — the objective
                    # being optimized, not a count-weighted variant.
                    total, accum = grad_norm
                    loss = ce_sum / total + aux / accum
                    loss_sum = ce_sum + aux * total / accum
            else:
                loss = ce.mean() + aux
                loss_sum = loss * tgt.size
            return loss, (hit, mutated.get("batch_stats", {}),
                          loss_sum)

        (_, (hit, new_stats, loss_sum)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        if packed:
            wt = _packed_target_weights(segs)
            n = jnp.sum(wt)
            correct = jnp.sum(hit * wt)
        else:
            n = hit.size
            correct = jnp.sum(hit)
        return grads, new_stats, M.from_batch(loss_sum, correct, n)

    def packed_count(y):
        return jnp.maximum(jnp.sum(_packed_target_weights(y)), 1.0)

    return _steps_from_micro(micro, max(1, optim_cfg.grad_accum), mesh,
                             gather_params=gather_params,
                             ema_decay=optim_cfg.ema_decay,
                             count_fn=packed_count if packed else None)


def make_lm_eval_step(model_cfg: Optional[ModelConfig] = None,
                      mesh=None, gather_params=None,
                      packed: bool = False) -> Callable:
    """eval_step(state, tokens, labels, mask) -> metrics; ``mask`` [B]
    zeroes padded sequences so the test set is counted exactly.
    ``packed=True``: ``labels`` carries [B, T] segment ids, composing
    the per-sequence mask with the per-token packing weights.
    ``gather_params``: FSDP compute-layout tree, same as the train step
    (without it the eval forward re-runs under the pathological GSPMD
    propagation the train step avoids). ``model_cfg`` + ``mesh``:
    --vocab-ce resolution, mirroring the train step (the eval forward
    is where full logits would otherwise peak at the same size)."""
    from tpunet.ops.vocab_ce import resolve_vocab_ce, vocab_parallel_ce
    sharded_ce = (model_cfg is not None
                  and resolve_vocab_ce(model_cfg.vocab_ce, mesh,
                                       model_cfg.vocab_size) == "sharded")

    @jax.named_scope("tpunet_eval_forward")
    def eval_step(state: TrainState, tokens, labels, mask):
        params = state.params
        if gather_params is not None:
            params = jax.lax.with_sharding_constraint(params, gather_params)
        kwargs = {"segment_ids": labels} if packed else {}
        tgt = tokens[:, 1:]
        if sharded_ce:
            h = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                tokens, train=False, return_hidden=True, **kwargs)
            losses, correct = vocab_parallel_ce(
                h[:, :-1], params["embed"]["embedding"], tgt, mesh)
        else:
            logits = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                tokens, train=False, **kwargs)
            lg = logits[:, :-1]
            losses = optax.softmax_cross_entropy_with_integer_labels(
                lg, tgt)
            correct = (jnp.argmax(lg, -1) == tgt).astype(jnp.float32)
        wt = mask[:, None]
        if packed:
            wt = wt * _packed_target_weights(labels)
        return M.from_batch(jnp.sum(losses * wt), jnp.sum(correct * wt),
                            jnp.sum(wt) if packed
                            else jnp.sum(wt) * tgt.shape[1])

    return eval_step


def make_eval_step(data_cfg: DataConfig, gather_params=None) -> Callable:
    """Build eval_step(state, images_u8, labels, mask) -> metrics.

    ``mask`` zeroes padded examples so the test set is counted exactly
    (fixes the reference's local-approximate accuracy, :196,224).
    ``gather_params``: FSDP compute-layout tree, as in the train step.
    """
    preprocess = make_eval_preprocess(data_cfg)

    @jax.named_scope("tpunet_eval_forward")
    def eval_step(state: TrainState, images_u8, labels, mask):
        params = state.params
        if gather_params is not None:
            params = jax.lax.with_sharding_constraint(params, gather_params)
        images = preprocess(images_u8)
        logits = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            images, train=False)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels)
        correct = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        return M.from_batch(jnp.sum(losses * mask),
                            jnp.sum(correct * mask),
                            jnp.sum(mask))

    return eval_step
