"""Jitted train / eval steps.

The reference's hot loop (cifar10_mpi_mobilenet_224.py:173-185) is:
h2d copy -> zero_grad -> DDP forward -> CE loss -> backward (bucketed
NCCL allreduce hooks) -> Adam step -> metric accumulation. Here the
entire iteration — on-device augmentation, forward, loss, backward,
cross-device gradient reduction, optimizer update, metric sums — is ONE
jitted XLA program per step; the gradient all-reduce is inserted by XLA
from the sharding layout (batch on the 'data' mesh axis, params
replicated) rather than by framework hooks.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from tpunet.config import DataConfig, ModelConfig, OptimConfig
from tpunet.data.augment import make_eval_preprocess, make_train_augment
from tpunet.train import metrics as M
from tpunet.train.state import TrainState


def _ce_loss(logits, targets, smoothing: float):
    """Per-example/token CE, optionally label-smoothed (StepLR stack's
    CrossEntropyLoss analogue; shared by the image and LM steps)."""
    if smoothing > 0:
        return optax.softmax_cross_entropy(
            logits, optax.smooth_labels(
                jax.nn.one_hot(targets, logits.shape[-1]), smoothing))
    return optax.softmax_cross_entropy_with_integer_labels(logits, targets)


def _with_aux(loss, mutated, aux_weight: float):
    """Add weighted MoE load-balance terms sown into 'losses'."""
    aux_terms = jax.tree_util.tree_leaves(mutated.get("losses", {}))
    if aux_terms and aux_weight > 0:
        loss = loss + aux_weight * sum(aux_terms)
    return loss


def make_train_step(data_cfg: DataConfig,
                    optim_cfg: OptimConfig,
                    model_cfg: Optional[ModelConfig] = None) -> Callable:
    """Build train_step(state, images_u8, labels, rng) -> (state, metrics).

    ``images_u8`` is the raw (global_batch, 32, 32, 3) uint8 batch;
    augmentation runs inside the step (fused by XLA with the forward).
    """
    augment = make_train_augment(data_cfg)
    smoothing = optim_cfg.label_smoothing
    aux_weight = model_cfg.moe_aux_weight if model_cfg is not None else 0.0

    def train_step(state: TrainState, images_u8, labels, rng):
        aug_rng, dropout_rng = jax.random.split(rng)
        images = augment(aug_rng, images_u8)

        def loss_fn(params):
            # mutable=["batch_stats"] is harmless for models without
            # BatchNorm (ViT): the mutated collection comes back empty.
            # "losses" carries MoE load-balance terms sown by MoeMlp.
            logits, mutated = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True,
                rngs={"dropout": dropout_rng},
                mutable=["batch_stats", "losses"])
            loss = _with_aux(_ce_loss(logits, labels, smoothing).mean(),
                             mutated, aux_weight)
            return loss, (logits, mutated.get("batch_stats", {}))

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        state = state.apply_gradients(grads=grads, batch_stats=new_stats)
        n = labels.shape[0]
        correct = jnp.sum(jnp.argmax(logits, -1) == labels)
        return state, M.from_batch(loss * n, correct, n)

    return train_step


def make_lm_train_step(optim_cfg: OptimConfig,
                       model_cfg: ModelConfig) -> Callable:
    """train_step(state, tokens, _labels, rng) -> (state, metrics) for
    the LM family: targets are the input shifted by one; metrics count
    next-token predictions (accuracy ~0.8 is ceiling on the synthetic
    bigram data, tpunet/data/lm.py)."""
    aux_weight = model_cfg.moe_aux_weight
    smoothing = optim_cfg.label_smoothing

    def train_step(state: TrainState, tokens, _labels, rng):
        def loss_fn(params):
            logits, mutated = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                tokens, train=True,
                rngs={"dropout": rng},
                mutable=["batch_stats", "losses"])
            lg, tgt = logits[:, :-1], tokens[:, 1:]
            loss = _with_aux(_ce_loss(lg, tgt, smoothing).mean(),
                             mutated, aux_weight)
            return loss, (lg, tgt, mutated.get("batch_stats", {}))

        (loss, (lg, tgt, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        state = state.apply_gradients(grads=grads, batch_stats=new_stats)
        n = tgt.size
        correct = jnp.sum(jnp.argmax(lg, -1) == tgt)
        return state, M.from_batch(loss * n, correct, n)

    return train_step


def make_lm_eval_step() -> Callable:
    """eval_step(state, tokens, _labels, mask) -> metrics; ``mask`` [B]
    zeroes padded sequences so the test set is counted exactly."""

    def eval_step(state: TrainState, tokens, _labels, mask):
        logits = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            tokens, train=False)
        lg, tgt = logits[:, :-1], tokens[:, 1:]
        losses = optax.softmax_cross_entropy_with_integer_labels(lg, tgt)
        wt = mask[:, None]
        correct = (jnp.argmax(lg, -1) == tgt).astype(jnp.float32)
        return M.from_batch(jnp.sum(losses * wt), jnp.sum(correct * wt),
                            jnp.sum(wt) * tgt.shape[1])

    return eval_step


def make_eval_step(data_cfg: DataConfig) -> Callable:
    """Build eval_step(state, images_u8, labels, mask) -> metrics.

    ``mask`` zeroes padded examples so the test set is counted exactly
    (fixes the reference's local-approximate accuracy, :196,224).
    """
    preprocess = make_eval_preprocess(data_cfg)

    def eval_step(state: TrainState, images_u8, labels, mask):
        images = preprocess(images_u8)
        logits = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            images, train=False)
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels)
        correct = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        return M.from_batch(jnp.sum(losses * mask),
                            jnp.sum(correct * mask),
                            jnp.sum(mask))

    return eval_step
