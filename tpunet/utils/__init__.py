from tpunet.utils.logging import epoch_line, log0, is_coordinator  # noqa: F401
from tpunet.utils.prng import epoch_key, step_key  # noqa: F401
from tpunet.utils.timing import Timer  # noqa: F401
