"""Shared persistent-compile-cache convention + AOT program store.

ONE home for the cache path and thresholds: tests/conftest.py,
tests/_mp_worker.py and __graft_entry__.py all call this, so every
entry point reads and warms the SAME per-user cache directory —
cross-process warm-cache hits (two multi-controller workers compiling
identical programs; a dryrun following a test run) depend on the
convention never diverging between copies.

The AOT store (``AotProgramStore``) is the stronger form the serving
tier needs: the persistent compilation cache still pays tracing +
lowering + a cache probe per program at every boot, but a replica's
program set is CLOSED (one decode step + one program per prefill
bucket), so the whole ``jax.jit(...).lower().compile()`` result can be
serialized once (``jax.experimental.serialize_executable``) and
deserialized at boot — no tracing, no lowering, no XLA invocation.
That is what turns replica cold-start from compile-bound minutes into
seconds and makes the router tier's scale-up decisions actionable
(docs/serving.md "AOT warm-start"). Entries are keyed by a caller-
supplied config digest + program shape + jax version + backend, so a
changed model config or runtime can never load a stale executable.
"""

from __future__ import annotations

import contextlib
import getpass
import hashlib
import os
import pickle
import tempfile

from tpunet.utils import fsatomic


def cache_dir() -> str:
    """The shared cache directory (honoring JAX's own env var) — also
    what subprocess launchers export as JAX_COMPILATION_CACHE_DIR."""
    return os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        tempfile.gettempdir(), f"tpunet-jax-cache-{getpass.getuser()}")


def enable_persistent_compile_cache(directory: str | None = None) -> None:
    """Point JAX's compiled-program cache at a shared per-user dir.

    JAX's own ``JAX_COMPILATION_CACHE_DIR`` env var wins when set (the
    operator relocated the cache); thresholds are lowered so every
    Trainer program is cached, not just multi-second compiles. Call
    AFTER jax is importable, BEFORE the first compile.

    ``directory`` overrides the default per-user tempdir (still losing
    to the env var) — the TPU entry points (bench.py, scripts/
    roofline_attrib.py) pass the repo-local ``.jax_cache``, which
    survives tempdir cleanup between sessions; remote-relay TPU
    compiles are expensive enough to deserve the more durable home.
    """
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_COMPILATION_CACHE_DIR") or directory
        or cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _reset_compilation_cache_latch() -> None:
    """Drop jax's once-per-process cache-usage latch.

    ``compile_or_get_cached`` gates on ``is_cache_used()``, which
    checks ``jax_enable_compilation_cache`` ONCE and latches the
    answer for the life of the process — after any compile has run
    with the cache enabled, flipping the flag off is silently ignored
    for both reads and writes. ``reset_cache()`` clears the latch (and
    the lazily-held cache handle) so the next compile re-evaluates the
    flag. Best-effort: on a jax without it, the flag flip alone still
    covers processes whose first compile is the serializable one."""
    try:
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except Exception:  # noqa: BLE001 — private API moved/renamed
        pass


@contextlib.contextmanager
def serializable_compile():
    """Compile with the persistent compilation cache OFF.

    An executable whose compile was SERVED from XLA's persistent cache
    serializes without error into a blob that fails
    ``deserialize_and_load`` at the next boot ("Symbols not found:
    [..._fusion ...]"), silently poisoning the AOT store. Wrap the
    ``.lower().compile()`` of any program destined for ``save`` in
    this so the executable is built fresh and self-contained; the
    cache setting is restored on exit.

    The flag flip alone is NOT enough: jax latches is-the-cache-used
    at the process's first compile, so a boot that compiled anything
    before this point would keep reading (and writing) the cache with
    the flag down — the latch is reset on entry and again on exit so
    both sides see their own flag honestly.
    """
    import jax

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    _reset_compilation_cache_latch()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        _reset_compilation_cache_latch()


class AotProgramStore:
    """Serialize/deserialize fully-compiled jax executables on disk.

    One store = one directory of ``<key>.aotx`` files, each a pickled
    ``(serialized_executable, in_tree, out_tree)`` triple from
    ``jax.experimental.serialize_executable.serialize``. The key folds
    in the caller's config digest (model architecture + pool shape),
    the program name and shape tag, the jax version, and the backend's
    device kind — any mismatch is a clean MISS, never a wrong program.

    ``load`` returns the loaded executable or None; ``save`` is
    best-effort (a read-only disk degrades to the persistent
    compilation cache, not to a crash). Both are torn-write-safe
    (tmp + rename) like every other artifact writer in the repo.
    """

    SUFFIX = ".aotx"

    def __init__(self, directory: str, config_digest: str):
        self.directory = directory
        self.config_digest = config_digest

    @staticmethod
    def digest(parts: object) -> str:
        """Stable 16-hex digest of a JSON-able description (the model/
        pool config fields that select a program)."""
        import json
        blob = json.dumps(parts, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def _path(self, name: str, shape_tag: str) -> str:
        import jax
        runtime = self.digest({
            "jax": jax.__version__,
            "device_kind": jax.devices()[0].device_kind,
            "n_devices": jax.device_count(),
        })
        key = f"{name}-{shape_tag}-{self.config_digest}-{runtime}"
        return os.path.join(self.directory, key + self.SUFFIX)

    def load(self, name: str, shape_tag: str):
        """The deserialized executable, or None on miss/corruption
        (a corrupt entry is removed so the next save rewrites it)."""
        path = self._path(name, shape_tag)
        if not os.path.exists(path):
            return None
        from jax.experimental import serialize_executable
        try:
            with open(path, "rb") as f:
                blob, in_tree, out_tree = pickle.load(f)
            return serialize_executable.deserialize_and_load(
                blob, in_tree, out_tree)
        except Exception:  # noqa: BLE001 — a stale/corrupt entry must
            # degrade to a recompile, never kill the boot.
            try:
                os.unlink(path)
            except OSError:
                pass
            return None

    def save(self, name: str, shape_tag: str, compiled) -> bool:
        """Serialize one compiled executable; best-effort (False on
        any failure — the persistent compilation cache still covers
        the next boot).

        Shared-filesystem safe: a multi-host fleet pointing N
        replicas at ONE ``--aot-cache`` dir all computes the same
        entry key, so the commit is deduplicated — the payload is
        staged under its CONTENT digest (two hosts serializing
        concurrently never collide on the tmp name) and committed
        under an ``flock``-guarded check: whichever host wins writes
        once, every later writer sees the committed entry and returns
        without touching the file. Still torn-write-safe (tmp +
        rename) like every other artifact writer in the repo."""
        from jax.experimental import serialize_executable
        try:
            blob, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            # Prove the roundtrip NOW: a cache-served executable (see
            # serializable_compile) serializes without error into a
            # blob that cannot be loaded back — a boot must never
            # trust an entry that was not load-verified at save time.
            serialize_executable.deserialize_and_load(
                blob, in_tree, out_tree)
            payload = pickle.dumps((blob, in_tree, out_tree))
            # First-writer-wins dedup + content-digest staging lives in
            # fsatomic — the prefix KV spill store shares the identical
            # commit discipline.
            return fsatomic.publish_bytes(
                self._path(name, shape_tag), payload)
        except Exception:  # noqa: BLE001
            return False
