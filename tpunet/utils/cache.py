"""Shared persistent-compile-cache convention.

ONE home for the cache path and thresholds: tests/conftest.py,
tests/_mp_worker.py and __graft_entry__.py all call this, so every
entry point reads and warms the SAME per-user cache directory —
cross-process warm-cache hits (two multi-controller workers compiling
identical programs; a dryrun following a test run) depend on the
convention never diverging between copies.
"""

from __future__ import annotations

import getpass
import os
import tempfile


def cache_dir() -> str:
    """The shared cache directory (honoring JAX's own env var) — also
    what subprocess launchers export as JAX_COMPILATION_CACHE_DIR."""
    return os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        tempfile.gettempdir(), f"tpunet-jax-cache-{getpass.getuser()}")


def enable_persistent_compile_cache(directory: str | None = None) -> None:
    """Point JAX's compiled-program cache at a shared per-user dir.

    JAX's own ``JAX_COMPILATION_CACHE_DIR`` env var wins when set (the
    operator relocated the cache); thresholds are lowered so every
    Trainer program is cached, not just multi-second compiles. Call
    AFTER jax is importable, BEFORE the first compile.

    ``directory`` overrides the default per-user tempdir (still losing
    to the env var) — the TPU entry points (bench.py, scripts/
    roofline_attrib.py) pass the repo-local ``.jax_cache``, which
    survives tempdir cleanup between sessions; remote-relay TPU
    compiles are expensive enough to deserve the more durable home.
    """
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_COMPILATION_CACHE_DIR") or directory
        or cache_dir())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
