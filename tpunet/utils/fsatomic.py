"""Shared-filesystem atomic publish: content-digest tmp + rename,
flock-guarded first-writer-wins.

ONE home for the commit discipline every fleet-shared artifact writer
needs (docs/serving.md "AOT warm-start" proved it for compiled
executables; the prefix KV store reuses it for spilled pages): a
multi-host fleet pointing N replicas at ONE shared directory all
computes the same entry key, so the commit must be deduplicated —
the payload is staged under its CONTENT digest (two hosts writing
concurrently never collide on the tmp name) and committed under an
``flock``-guarded exists-check: whichever host wins writes once,
every later writer sees the committed entry and returns without
touching the file. Torn-write-safe (tmp + ``os.replace``) like every
other artifact writer in the repo; on filesystems/platforms without
flock the rename commit alone still guarantees no torn entry — only
the dedup check loses its atomicity.
"""

from __future__ import annotations

import contextlib
import hashlib
import os


@contextlib.contextmanager
def commit_lock(path: str):
    """``flock`` on ``<entry>.lock`` around an exists-check + rename
    (advisory, NFS-visible where flock is supported)."""
    lock_path = path + ".lock"
    try:
        import fcntl
    except ImportError:          # non-POSIX: rename-only safety
        yield
        return
    with open(lock_path, "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)


def publish_bytes(path: str, payload: bytes) -> bool:
    """Commit ``payload`` at ``path`` exactly once across the fleet.

    True when the entry exists on return (this writer won, or an
    earlier one did — an existing entry is NEVER rewritten: a replica
    may be reading it right now). The parent directory is created on
    demand; any OS failure propagates to the caller, who decides
    whether the artifact is best-effort.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with commit_lock(path):
        if os.path.exists(path):
            return True
        content = hashlib.sha256(payload).hexdigest()[:16]
        tmp = path + f".{content}.tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    return True
