"""Rank-0 logging with the reference's epoch-line format.

The reference prints one line per epoch from rank 0 only
(cifar10_mpi_mobilenet_224.py:229-236), captured by SLURM stdout
redirection. Serial format (cifar10_128_gpu_27326.out:30):

    Epoch 1/20 Time: 570.94s Train Loss: 0.5879 Train Acc: 0.8007 \
Test Loss: 0.2834 Test Acc: 0.9027

We replicate that format exactly so runs are directly comparable with the
reference's published logs. Unlike the reference's distributed mode (which
printed a rank-local "Test Acc(local)", :196,224), our accuracy is always
globally reduced, so we always use the serial field names.
"""

from __future__ import annotations

import sys

import jax


def is_coordinator() -> bool:
    """True on the process allowed to do I/O (reference rank==0 guards)."""
    return jax.process_index() == 0


def log0(*args, **kwargs) -> None:
    """Print from the coordinator process only; flush for SLURM-style logs."""
    if is_coordinator():
        print(*args, **kwargs)
        sys.stdout.flush()


def epoch_line(epoch: int, epochs: int, seconds: float, train_loss: float,
               train_acc: float, test_loss: float, test_acc: float) -> str:
    return (
        f"Epoch {epoch}/{epochs} Time: {seconds:.2f}s "
        f"Train Loss: {train_loss:.4f} Train Acc: {train_acc:.4f} "
        f"Test Loss: {test_loss:.4f} Test Acc: {test_acc:.4f}"
    )


def summary_lines(best_acc: float, total_seconds: float) -> list[str]:
    """Reference end-of-run lines (cifar10_128_gpu_27326.out:51-52)."""
    return [
        f"Best test accuracy: {best_acc:.4f}",
        f"Total training time: {total_seconds:.2f}s ({total_seconds / 60:.2f} min)",
    ]


class MetricsLogger:
    """Machine-readable observability: one JSON line per epoch, appended
    to ``<dir>/metrics.jsonl`` by the coordinator process. The reference
    persists metrics only as SLURM stdout redirection of the epoch lines
    (cifar10_gpu_parallel.sh:8-9); this is the structured upgrade —
    append-mode + per-line flush keeps it crash/preemption-safe."""

    def __init__(self, directory: str, resume: bool = False):
        import os
        self._path = None
        if is_coordinator():
            os.makedirs(directory, exist_ok=True)
            self._path = os.path.join(directory, "metrics.jsonl")
            if not resume and os.path.exists(self._path):
                # Fresh run into a reused directory: truncate so the
                # epoch sequence in the file belongs to one run.
                open(self._path, "w").close()

    def log(self, record: dict) -> None:
        if self._path is None:
            return
        import json
        with open(self._path, "a") as f:
            # ONE write of the full line: a crash can truncate the last
            # record but never interleave two (append-mode writes of a
            # single buffer are atomic for sane line sizes).
            f.write(json.dumps(record) + "\n")

    @classmethod
    def tail_records(cls, path: str, offset: int = 0) -> tuple:
        """Incremental read for live consumers: parse complete records
        appended since ``offset`` and return
        ``(records, new_offset, reset)``.

        The trailing partial line (a write in flight, or a torn write
        after a crash) is NOT consumed — the returned offset points at
        its start, so the next poll re-reads it once it is complete.
        A file that shrank below ``offset`` (fresh run truncated it)
        restarts from the beginning and reports ``reset=True`` so the
        caller can discard state derived from the old run's records —
        the check lives HERE, on the same stat the read uses, so no
        caller-side check can race it. A malformed *complete* line is
        skipped, not fatal: a live dashboard must outlive one bad
        record."""
        import json
        import os as _os
        records = []
        reset = False
        try:
            size = _os.path.getsize(path)
        except OSError:
            return records, 0, offset > 0
        if size < offset:
            offset = 0          # truncated underneath us: new run
            reset = True
        if size == offset:
            return records, offset, reset
        with open(path, "rb") as f:
            f.seek(offset)
            chunk = f.read()
        end = chunk.rfind(b"\n")
        if end < 0:
            return records, offset, reset   # only a partial line so far
        for line in chunk[:end].splitlines():
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return records, offset + end + 1, reset

    @classmethod
    def read_records(cls, path: str) -> list:
        """Parse a ``metrics.jsonl`` back into dicts, tolerating a
        truncated trailing line (the crash/preemption artifact the
        append-per-record format can leave). A malformed line anywhere
        *else* is real corruption and raises."""
        import json
        records = []
        with open(path) as f:
            lines = f.read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    break  # torn trailing write: drop it
                raise ValueError(
                    f"{path}:{i + 1}: malformed record mid-file (only "
                    f"the final line may be truncated)")
        return records
