"""Graceful-preemption handling.

The reference has no failure story at all — a SLURM walltime kill or
node preemption loses everything since the epoch-0 restart is the only
path (SURVEY.md section 5). TPU-VM spot/preemptible instances send
SIGTERM with a short grace window; this guard turns that into a clean
stop: the signal sets a flag, the Trainer notices it between steps,
saves a full-state checkpoint and exits, and ``--resume`` continues.

The handler only sets a flag (async-signal-safe); all real work happens
on the main thread at a step boundary. Previous handlers are chained so
embedding tpunet in a larger program keeps its signal behavior.
"""

from __future__ import annotations

import signal
from typing import Iterable, Optional


class PreemptionGuard:
    """Install with ``install()``; poll ``requested`` between steps."""

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._previous: Optional[dict] = None
        self.requested = False

    def _handler(self, signum, frame):
        self.requested = True
        prev = (self._previous or {}).get(signum)
        if callable(prev):
            prev(signum, frame)

    def request(self) -> None:
        """Programmatic stop request (same path as a signal)."""
        self.requested = True

    def install(self) -> "PreemptionGuard":
        if self._previous is None:
            self._previous = {}
            for s in self._signals:
                self._previous[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self) -> None:
        if self._previous is not None:
            for s, prev in self._previous.items():
                signal.signal(s, prev if prev is not None else signal.SIG_DFL)
            self._previous = None

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
