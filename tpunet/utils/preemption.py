"""Graceful-preemption handling.

The reference has no failure story at all — a SLURM walltime kill or
node preemption loses everything since the epoch-0 restart is the only
path (SURVEY.md section 5). TPU-VM spot/preemptible instances send
SIGTERM with a short grace window; this guard turns that into a clean
stop: the signal sets a flag, the Trainer notices it between steps,
saves a full-state checkpoint and exits, and ``--resume`` continues.

Grace-window discipline (docs/elasticity.md):

- ``deadline_s`` tells the guard how much grace the platform grants
  after the first SIGTERM. ``remaining()`` is then the budget the
  trainer has left — it skips the eval pass when preempted and bounds
  the checkpoint-durability wait to the remaining grace instead of
  blocking past the platform's kill.
- a **second** SIGTERM during the grace window escalates
  (``escalated``): the platform (or an impatient operator) is saying
  "now", so the trainer abandons the in-flight checkpoint work and
  exits immediately instead of finishing a save that will be
  SIGKILLed mid-write anyway. (Previously a repeat signal was
  silently absorbed by the already-set flag.)

The handler only sets flags and reads a monotonic clock
(async-signal-safe); all real work happens on the main thread at a
step boundary. Previous handlers are chained so embedding tpunet in a
larger program keeps its signal behavior.
"""

from __future__ import annotations

import signal
import time
from typing import Callable, Iterable, Optional


class PreemptionGuard:
    """Install with ``install()``; poll ``requested`` / ``escalated``
    between steps; budget shutdown work with ``remaining()``."""

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,),
                 deadline_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self._signals = tuple(signals)
        self._previous: Optional[dict] = None
        self._clock = clock
        self.deadline_s = float(deadline_s)
        self.requested = False
        self.escalated = False
        self.requested_at: Optional[float] = None

    def _handler(self, signum, frame):
        if self.requested:
            # Second signal inside the grace window: escalate. A
            # platform that double-signals means the window is over.
            self.escalated = True
        else:
            self.requested = True
            self.requested_at = self._clock()
        prev = (self._previous or {}).get(signum)
        if callable(prev):
            prev(signum, frame)

    def request(self, escalate: bool = False) -> None:
        """Programmatic stop request. Idempotent by default — the
        cross-host stop agreement re-requests every poll, and that
        must not count as a second preemption; pass ``escalate=True``
        to mirror a repeated signal."""
        if self.requested:
            if escalate:
                self.escalated = True
        else:
            self.requested = True
            self.requested_at = self._clock()

    def remaining(self) -> Optional[float]:
        """Grace seconds left (>= 0), or None when no deadline is
        configured or no preemption has been requested — callers pass
        it straight into bounded waits."""
        if not self.requested or self.deadline_s <= 0 \
                or self.requested_at is None:
            return None
        return max(0.0, self.deadline_s
                   - (self._clock() - self.requested_at))

    def install(self) -> "PreemptionGuard":
        if self._previous is None:
            self._previous = {}
            for s in self._signals:
                self._previous[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self) -> None:
        if self._previous is not None:
            for s, prev in self._previous.items():
                signal.signal(s, prev if prev is not None else signal.SIG_DFL)
            self._previous = None

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
