"""Deterministic PRNG plumbing.

The reference seeds every rank identically with torch.manual_seed(42)
(cifar10_mpi_mobilenet_224.py:58) and relies on DistributedSampler's
set_epoch for per-epoch reshuffles (:165). Here a single root key is
folded with the epoch (shuffle key) and with the global step (augmentation
key); per-example independence comes from vmap key splitting, so results
are identical regardless of mesh shape or host count.
"""

from __future__ import annotations

import jax


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def epoch_key(seed: int, epoch: int) -> jax.Array:
    """Key for the epoch-level shuffle (DistributedSampler.set_epoch analog)."""
    return jax.random.fold_in(root_key(seed), epoch)


def step_key(seed: int, step: int) -> jax.Array:
    """Key for per-step data augmentation; step is the global step counter."""
    return jax.random.fold_in(jax.random.fold_in(root_key(seed), 0x5EED), step)
