"""Wall-clock timing for the epoch/total timers and per-step laps.

``time.perf_counter`` throughout, not the reference's ``time.time``
(cifar10_mpi_mobilenet_224.py:161,164,227,242): perf_counter is
monotonic with the highest available resolution, so NTP clock steps on
a long-running host can never produce negative or wildly wrong epoch
times — and sub-millisecond step laps are actually resolvable.
"""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self.start = time.perf_counter()
        self._lap = self.start

    def reset(self) -> None:
        self.start = time.perf_counter()
        self._lap = self.start

    def elapsed(self) -> float:
        """Seconds since construction/reset (lap state untouched)."""
        return time.perf_counter() - self.start

    def lap(self) -> float:
        """Seconds since the previous ``lap()`` (or construction/
        reset) — the per-step accounting primitive."""
        now = time.perf_counter()
        dt = now - self._lap
        self._lap = now
        return dt
