"""Wall-clock timing, matching the reference's time.time() epoch/total
timers (cifar10_mpi_mobilenet_224.py:161,164,227,242)."""

from __future__ import annotations

import time


class Timer:
    def __init__(self):
        self.start = time.time()

    def reset(self) -> None:
        self.start = time.time()

    def elapsed(self) -> float:
        return time.time() - self.start
