#!/usr/bin/env python
"""tpunet training entry point (thin shim; the CLI lives in
tpunet/main.py so the installed ``tpunet-train`` console script and
``python train.py`` share one implementation)."""

import sys

from tpunet.main import main

if __name__ == "__main__":
    sys.exit(main())
